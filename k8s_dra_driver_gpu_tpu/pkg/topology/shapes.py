"""Sub-torus shape enumeration and wraparound-aware placements.

A "shape" is an axis-aligned (w, h, d) sub-box of the slice grid --
the TPU analog of a MIG profile: the units users actually claim
(1x1x1 single chip, 2x2x1 quad, 2x2x4 sub-cube, ... up to the full
slice). A "placement" is a concrete anchored instance of a shape; on a
wrapping axis anchors may run off the end and wrap around (the
placement stays ICI-contiguous through the wraparound link), so a
4-wide ring has 4 distinct 2-wide placements, not 3.
"""

from __future__ import annotations

from .grid import Coord, TorusGrid


def enumerate_shapes(grid: TorusGrid, max_chips: int | None = None
                     ) -> list[tuple[int, int, int]]:
    """Every sub-torus shape the grid admits, largest volume first
    (ties: more cubic first, then lexicographic). This is the shape
    catalog the fragmentation scorer protects."""
    x, y, z = grid.dims
    out = []
    for w in range(1, x + 1):
        for h in range(1, y + 1):
            for d in range(1, z + 1):
                vol = w * h * d
                if max_chips is not None and vol > max_chips:
                    continue
                out.append((w, h, d))
    out.sort(key=lambda s: (-(s[0] * s[1] * s[2]),
                            max(s) - min(s), s))
    return out


def shapes_for_count(grid: TorusGrid, count: int
                     ) -> list[tuple[int, int, int]]:
    """Shapes of exactly ``count`` chips that fit the grid, most
    compact first (min max-dimension, then min surface-to-volume --
    a 2x2x1 quad beats a 4x1x1 line)."""
    if count < 1:
        return []
    x, y, z = grid.dims
    out = []
    for w in range(1, x + 1):
        if count % w:
            continue
        rest = count // w
        for h in range(1, y + 1):
            if rest % h:
                continue
            d = rest // h
            if 1 <= d <= z:
                out.append((w, h, d))
    out.sort(key=lambda s: (max(s),
                            2 * (s[0] * s[1] + s[1] * s[2]
                                 + s[0] * s[2]), s))
    return out


def _axis_anchors(grid: TorusGrid, axis: int, size: int) -> range:
    n = grid.dims[axis]
    if size > n:
        return range(0)
    if size == n:
        # Full-axis spans at every anchor are the same cell set
        # (wrapped or not); one representative keeps placements unique.
        return range(1)
    if grid.wrap[axis]:
        return range(n)
    return range(n - size + 1)


def placements(grid: TorusGrid, shape: tuple[int, int, int]
               ) -> list[tuple[Coord, ...]]:
    """All distinct placements of ``shape``: each a tuple of cells in
    deterministic (z, y, x)-major order. Wrapping axes contribute
    anchors whose extent crosses the seam."""
    w, h, d = shape
    out: list[tuple[Coord, ...]] = []
    for az in _axis_anchors(grid, 2, d):
        for ay in _axis_anchors(grid, 1, h):
            for ax in _axis_anchors(grid, 0, w):
                cells = tuple(
                    ((ax + dx) % grid.dims[0],
                     (ay + dy) % grid.dims[1],
                     (az + dz) % grid.dims[2])
                    for dz in range(d)
                    for dy in range(h)
                    for dx in range(w)
                )
                out.append(cells)
    return out
