"""ICI topology-aware placement engine.

The driver publishes per-chip ICI coordinates (``iciX``/``iciY``/
``iciZ``, ``deviceinfo.py``) and the full-slice grid (``topology``),
but until this subsystem existed the in-tree DRA scheduler picked
devices first-fit -- a 4-chip claim could land scattered across the
torus, and churn steadily destroyed the large contiguous shapes that
multi-chip training claims need. This package is the placement layer:

- ``grid``      -- ``TorusGrid``: chip attributes -> a wraparound-aware
                   per-pool grid model (partial grids and chips with
                   missing coordinates are first-class).
- ``shapes``    -- valid sub-torus shape enumeration (1x1x1 .. full
                   slice) and wraparound-aware placement generation.
- ``score``     -- the placement scorer: candidate device sets ranked
                   by fragmentation cost (how many future large shapes
                   a pick destroys, best-fit-style) then compactness
                   (max ICI hop distance, exposed surface area).
- ``hosts``     -- multi-host gang support: rank hosts so a gang of N
                   lands on ICI-adjacent workers.
- ``sim``       -- the placement simulator: randomized claim
                   arrival/departure churn against v5e/v5p-shaped
                   grids, first-fit vs. scored, reporting
                   allocatable-largest-shape-over-time + fragmentation.

Design analog: the multi-objective MIG-fleet placement literature
(arXiv:2502.01909, ParvaGPU arXiv:2409.14447) -- keep allocations
compact AND keep the biggest future shapes allocatable. The scorer
only ORDERS candidates; correctness (constraints, counters, taints)
stays with the scheduler's backtracking fit, so first-fit semantics
are the automatic fallback whenever coordinates are absent or the
``TopologyAwarePlacement`` feature gate is off.
"""

from .grid import TorusGrid, default_wrap
from .hosts import rank_adjacent_hosts
from .score import (
    fragmentation_score,
    largest_free_shape,
    order_candidates,
    rank_placements,
    set_compactness,
)
from .shapes import enumerate_shapes, placements, shapes_for_count

__all__ = [
    "TorusGrid",
    "default_wrap",
    "enumerate_shapes",
    "fragmentation_score",
    "largest_free_shape",
    "order_candidates",
    "placements",
    "rank_adjacent_hosts",
    "rank_placements",
    "set_compactness",
    "shapes_for_count",
]
