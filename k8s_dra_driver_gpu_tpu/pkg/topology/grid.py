"""Torus grid model parsed from published DRA device attributes.

A ``TorusGrid`` is the scheduler-side view of one resource pool's ICI
fabric: the full-slice dimensions (from the ``topology`` attribute,
e.g. ``"4x4"`` or ``"2x2x4"``), per-axis wraparound links, and a
name -> (x, y, z) coordinate map for every chip that published usable
``iciX``/``iciY``/``iciZ`` attributes. Devices without coordinates
(sub-slice carve-outs, daemon/channel devices, degraded publications)
are kept aside in ``uncoordinated`` -- they always fall back to
first-fit ordering, never poison the grid.

The grid may be PARTIAL: a multi-host slice publishes one pool per
node, each carrying only that host's chips at their global slice
coordinates. Dims always describe the full slice, so hop distances and
wraparound stay correct even when only a 2x2 corner of a 4x4 slice is
visible.
"""

from __future__ import annotations

from dataclasses import dataclass, field

Coord = tuple[int, int, int]

# Generations whose ICI fabric is a 3D torus (the 2D generations are
# meshes that only close into rings at full-pod scale).
_THREE_D_PLATFORMS = frozenset({"v4", "v5", "v5p"})


def default_wrap(platform: str, dims: tuple[int, int, int]
                 ) -> tuple[bool, bool, bool]:
    """Per-axis wraparound policy by TPU generation.

    3D-torus generations (v4/v5p) ship wraparound links on any axis of
    length >= 4 (production slices are built from 4-multiples); the 2D
    generations (v5e/v6e) are meshes whose axes only close into rings
    at the full 16-wide pod dimension. Axes of length <= 2 never wrap
    (a "ring" of 2 is just the existing link). Unknown platforms get
    the conservative no-wrap model -- distances can only be
    overestimated, never underestimated.
    """
    if platform in _THREE_D_PLATFORMS:
        return tuple(n >= 4 for n in dims)  # type: ignore[return-value]
    if platform:  # known 2D generations and anything else named
        return tuple(n >= 16 for n in dims)  # type: ignore[return-value]
    return (False, False, False)


def attr_int(attrs: dict, name: str) -> int | None:
    """A device attribute as an int: accepts the typed DRA form
    ({"int": 3}) and a bare int (internal callers). THE typed-int
    unwrapping rule -- reuse it instead of re-implementing (the CD
    controller's workerId parsing goes through here too)."""
    entry = attrs.get(name)
    if isinstance(entry, dict):
        entry = entry.get("int")
    if isinstance(entry, bool) or not isinstance(entry, int):
        return None
    return entry


def _attr_str(attrs: dict, name: str) -> str | None:
    entry = attrs.get(name)
    if isinstance(entry, dict):
        entry = entry.get("string")
    return entry if isinstance(entry, str) else None


def parse_dims(topology: str) -> tuple[int, int, int] | None:
    """``"4x4"`` -> (4, 4, 1); ``"2x2x4"`` -> (2, 2, 4); None when the
    string is not a well-formed positive grid."""
    parts = topology.split("x")
    if not 1 <= len(parts) <= 3:
        return None
    try:
        dims = [int(p) for p in parts]
    except ValueError:
        return None
    if any(d < 1 for d in dims):
        return None
    while len(dims) < 3:
        dims.append(1)
    return (dims[0], dims[1], dims[2])


@dataclass(frozen=True)
class TorusGrid:
    """One pool's ICI grid: full-slice dims, wraparound, chip coords."""

    dims: tuple[int, int, int]
    wrap: tuple[bool, bool, bool] = (False, False, False)
    # chip canonical name -> global slice coordinate
    coords: dict[str, Coord] = field(default_factory=dict)
    # devices that carried no usable coordinates (first-fit fallback)
    uncoordinated: tuple[str, ...] = ()

    @classmethod
    def from_devices(cls, devices: list[dict],
                     wrap: tuple[bool, bool, bool] | None = None,
                     ) -> "TorusGrid":
        """Build a grid from DRA Device dicts (``name`` + typed
        ``attributes``).

        Dims come from the first well-formed ``topology`` attribute;
        with none published, the bounding box of the seen coordinates.
        A device is coordinated when iciX and iciY parse as ints (iciZ
        defaults to 0 for 2D grids); duplicate or out-of-grid
        coordinates demote the later device to ``uncoordinated`` --
        a half-trusted grid would mis-rank everything.
        """
        dims: tuple[int, int, int] | None = None
        platform = ""
        raw: list[tuple[str, Coord | None]] = []
        for dev in devices:
            attrs = dev.get("attributes") or {}
            if dims is None:
                topo = _attr_str(attrs, "topology")
                if topo:
                    dims = parse_dims(topo)
            if not platform:
                platform = _attr_str(attrs, "platform") or ""
            x = attr_int(attrs, "iciX")
            y = attr_int(attrs, "iciY")
            z = attr_int(attrs, "iciZ")
            name = dev.get("name", "")
            if x is None or y is None or not name:
                raw.append((name, None))
            else:
                raw.append((name, (x, y, z if z is not None else 0)))
        if dims is None:
            seen = [c for _, c in raw if c is not None]
            if seen:
                dims = (max(c[0] for c in seen) + 1,
                        max(c[1] for c in seen) + 1,
                        max(c[2] for c in seen) + 1)
            else:
                dims = (1, 1, 1)
        coords: dict[str, Coord] = {}
        taken: set[Coord] = set()
        uncoordinated: list[str] = []
        for name, c in raw:
            if (c is None or c in taken
                    or any(not 0 <= c[i] < dims[i] for i in range(3))):
                if name:
                    uncoordinated.append(name)
                continue
            coords[name] = c
            taken.add(c)
        if wrap is None:
            wrap = default_wrap(platform, dims)
        return cls(dims=dims, wrap=wrap, coords=coords,
                   uncoordinated=tuple(uncoordinated))

    # -- geometry -------------------------------------------------------------

    def axis_distance(self, axis: int, a: int, b: int) -> int:
        d = abs(a - b)
        if self.wrap[axis]:
            d = min(d, self.dims[axis] - d)
        return d

    def hop_distance(self, a: Coord, b: Coord) -> int:
        """ICI hops between two chips (L1 on the torus)."""
        return sum(self.axis_distance(i, a[i], b[i]) for i in range(3))

    def max_hops(self, cells: set[Coord] | list[Coord]) -> int:
        """Network diameter of a chip set (0 for <= 1 chip)."""
        cells = list(cells)
        best = 0
        for i, a in enumerate(cells):
            for b in cells[i + 1:]:
                d = self.hop_distance(a, b)
                if d > best:
                    best = d
        return best

    def neighbors(self, c: Coord) -> list[Coord]:
        """The <= 6 ICI neighbors of a cell (wraparound-aware, grid
        bounds enforced on non-wrapping axes)."""
        out = []
        for axis in range(3):
            n = self.dims[axis]
            if n == 1:
                continue
            for step in (-1, 1):
                v = c[axis] + step
                if self.wrap[axis]:
                    v %= n
                elif not 0 <= v < n:
                    continue
                nc = list(c)
                nc[axis] = v
                out.append((nc[0], nc[1], nc[2]))
        return out

    def surface_area(self, cells: set[Coord]) -> int:
        """Exposed ICI links of a set: for every member, each neighbor
        slot not also in the set. Lower = more compact (fewer fabric
        links crossing the allocation boundary)."""
        return sum(
            1
            for c in cells
            for n in self.neighbors(c)
            if n not in cells
        )

