"""The placement scorer: compactness first, fragmentation cost second.

Given a pool grid, its free chips, and a claim's chip count, the
scorer ranks candidate device sets by:

1. **Compactness**: max pairwise ICI hop distance (the collective's
   worst-case path -- the property the CLAIM's owner feels), then
   exposed surface area (fabric links crossing the allocation
   boundary). A 2x2 quad always beats a 4x1 line.
2. **Fragmentation cost** (best-fit): among equally-compact choices,
   how many future large-shape placements the pick destroys, weighted
   by shape volume -- the property the FLEET feels under churn. The
   protected-shape catalog is the power-of-two claim sizes (2, 4, 8,
   ... up to the slice) -- the sizes TPU sub-slices actually come in --
   which keeps scoring O(hundreds) of placement checks instead of the
   full shape lattice.
3. A deterministic name tiebreak, so equal-score rankings are stable
   across processes and test runs.

Exact sub-torus placements are preferred; when fragmentation (or a
non-factorizable count) leaves none, a greedy nearest-neighbor
fallback still produces compact -- just not box-shaped -- sets.
"""

from __future__ import annotations

import os
import threading
from functools import lru_cache

from .grid import Coord, TorusGrid
from .shapes import enumerate_shapes, placements, shapes_for_count


def attr_int(attrs: dict, name: str) -> int:
    """Quantized int device attribute (`{"int": N}` entries), 0 when
    absent/malformed — THE parse for the telemetry/power attribute
    contract, shared by the scorer, pkg/schedcache and
    pkg/fleetstate so the three readers can never drift."""
    entry = attrs.get(name)
    if isinstance(entry, dict) and "int" in entry:
        try:
            return int(entry["int"])
        except (TypeError, ValueError):
            return 0
    return 0


def set_compactness(grid: TorusGrid, cells: set[Coord]
                    ) -> tuple[int, int]:
    """(max ICI hops, exposed surface area) -- lower is tighter."""
    return (grid.max_hops(cells), grid.surface_area(cells))


# -- power / thermal headroom (2501.17752: telemetry as a placement
# signal). Chips in an active anomaly episode of these kinds carry a
# non-fatal ``tpu.dra.dev/<kind>`` device taint (pkg/anomaly.py via the
# health poll); the scorer treats them as last-resort picks. Pure
# PREFERENCE below the quarantine threshold: the fit semantics
# (selectors, counters, matchAttributes) never change -- a degraded
# chip is still used when no clean peer satisfies the claim.
AVOID_TAINT_KINDS = ("power_cap_throttle", "duty_cycle_straggler",
                     "thermal_drift")
#: Penalty weight of an active avoid-kind anomaly taint.
PENALTY_ANOMALY = 4
#: ...of low power headroom (telemetry draw near the node cap share).
PENALTY_POWER = 2
#: ...of low thermal headroom (die temp at/above the soft limit).
PENALTY_THERMAL = 1
#: Power-headroom threshold: telemetry draw >= this fraction of the
#: device's rated/cap share counts as "no headroom".
POWER_HEADROOM_FRACTION = 0.9


@lru_cache(maxsize=8)
def _parse_limit(raw: str) -> float:
    try:
        return max(float(raw), 0.0)
    except ValueError:
        return 0.0


def _soft_temp_limit_c() -> float:
    """``TPU_DRA_TEMP_SOFT_LIMIT_C``: die temperature above which a
    chip loses thermal-headroom preference (0 disables). Called per
    device per snapshot build: the env read stays live (tests flip
    it) but the parse is memoized on the raw string."""
    return _parse_limit(os.environ.get(
        "TPU_DRA_TEMP_SOFT_LIMIT_C", "0"))


def device_headroom_penalty(device: dict,
                            temp_limit_c: float | None = None) -> int:
    """Telemetry-derived placement penalty for one published device
    (0 = healthy). Summed per candidate placement by
    :func:`rank_placements` and used as a stable-sort key by the
    scheduler's fallback ordering -- higher sorts later, never out."""
    penalty = 0
    for taint in device.get("taints") or []:
        key = taint.get("key", "")
        if any(key.endswith("/" + kind) or key == kind
               for kind in AVOID_TAINT_KINDS):
            penalty += PENALTY_ANOMALY
            break  # one anomaly penalty per device, not per kind
    attrs = device.get("attributes") or {}
    power = attr_int(attrs, "telemetryPowerWatts")
    rated = attr_int(attrs, "powerRatedWatts")
    if power > 0 and rated > 0 and \
            power >= rated * POWER_HEADROOM_FRACTION:
        penalty += PENALTY_POWER
    temp = attr_int(attrs, "telemetryTempCelsius")
    limit = _soft_temp_limit_c() if temp_limit_c is None \
        else temp_limit_c
    if temp > 0 and limit > 0 and temp >= limit:
        penalty += PENALTY_THERMAL
    return penalty


def _protected_shapes(grid: TorusGrid) -> list[tuple[int, int, int]]:
    """The power-of-two shape catalog the frag scorer defends."""
    total = grid.dims[0] * grid.dims[1] * grid.dims[2]
    shapes: list[tuple[int, int, int]] = []
    size = 2
    while size <= total:
        shapes.extend(shapes_for_count(grid, size))
        size *= 2
    return shapes


def _free_placements(grid: TorusGrid, free: set[Coord],
                     shapes: list[tuple[int, int, int]]
                     ) -> list[tuple[int, frozenset[Coord]]]:
    """(volume, cells) for every protected placement currently fully
    free -- the standing inventory a pick can destroy."""
    out = []
    for shape in shapes:
        vol = shape[0] * shape[1] * shape[2]
        for cells in placements(grid, shape):
            if all(c in free for c in cells):
                out.append((vol, frozenset(cells)))
    return out


def frag_cost(pick: set[Coord],
              inventory: list[tuple[int, frozenset[Coord]]]) -> int:
    """Volume-weighted count of inventory placements the pick
    intersects (and therefore destroys)."""
    return sum(vol for vol, cells in inventory
               if not cells.isdisjoint(pick))


def grid_signature(grid: TorusGrid) -> tuple:
    """A hashable identity for a grid's geometry: dims, wraparound,
    and the coordinate map. A pure function of the published devices,
    so two grids built from the same slices share one memo row.

    Cached on the grid instance: TorusGrid is immutable after
    ``from_devices``, and the fleet fold + the defrag what-if loop
    query the same grid object many times per pass -- without the
    cache the O(n log n) coord sort would dominate every memo hit.
    (``object.__setattr__``: the dataclass is frozen, which blocks
    the normal spelling but not this deliberate one-shot memo.)"""
    sig = getattr(grid, "_signature_memo", None)
    if sig is None:
        sig = (grid.dims, grid.wrap,
               tuple(sorted(grid.coords.items())))
        try:
            object.__setattr__(grid, "_signature_memo", sig)
        except (AttributeError, TypeError):
            pass  # slotted/odd grid subclass: recompute per call
    return sig


# largest_free_shape memo: (grid signature, frozenset(free)) ->
# (shape, chips). The FleetAggregator fold recomputes every pool's
# frag each pass and the defrag what-if loop probes dozens of
# hypothetical free sets against ONE grid -- without the memo each
# probe pays the full O(shapes x placements) sweep. Bounded FIFO
# (oldest third dropped at the cap) so a long-lived scheduler can't
# grow it without bound.
_SHAPE_MEMO: dict[tuple, tuple[tuple[int, int, int], int]] = {}
_SHAPE_MEMO_MAX = 4096
_shape_memo_lock = threading.Lock()


def clear_shape_memo() -> None:
    """Drop the largest_free_shape memo (tests / bench isolation)."""
    with _shape_memo_lock:
        _SHAPE_MEMO.clear()


def largest_free_shape(grid: TorusGrid, free: set[Coord]
                       ) -> tuple[tuple[int, int, int], int]:
    """The biggest sub-torus shape still fully placeable in ``free``
    -> (shape, chips); ((0, 0, 0), 0) when nothing is free.

    Memoized on (grid signature, free set): the sweep is the most
    expensive topology operation, and both the fleet fold and the
    defrag planner call it repeatedly with recurring inputs."""
    key = (grid_signature(grid), frozenset(free))
    with _shape_memo_lock:
        hit = _SHAPE_MEMO.get(key)
    if hit is not None:
        return hit
    result: tuple[tuple[int, int, int], int] = ((0, 0, 0), 0)
    for shape in enumerate_shapes(grid, max_chips=len(free)):
        placed = False
        for cells in placements(grid, shape):
            if all(c in free for c in cells):
                result = (shape, shape[0] * shape[1] * shape[2])
                placed = True
                break
        if placed:
            break
    with _shape_memo_lock:
        if len(_SHAPE_MEMO) >= _SHAPE_MEMO_MAX:
            for old in list(_SHAPE_MEMO)[:_SHAPE_MEMO_MAX // 3]:
                del _SHAPE_MEMO[old]
        _SHAPE_MEMO[key] = result
    return result


def frag_from_largest(largest_chips: int, free_count: int) -> float:
    """THE fragmentation formula: 1 - largest-allocatable-shape /
    free-chips, in [0, 1). Exposed separately so callers that already
    paid the largest_free_shape sweep (the expensive half) don't
    re-derive -- or worse, re-implement -- the division."""
    if free_count <= 0:
        return 0.0
    return 1.0 - largest_chips / free_count


def fragmentation_score(grid: TorusGrid, free: set[Coord]) -> float:
    """0.0 means the free space is one perfect sub-torus (or there is
    none); rising values mean churn has shredded the big shapes."""
    _, chips = largest_free_shape(grid, free)
    return frag_from_largest(chips, len(free))


def _greedy_sets(grid: TorusGrid, free: set[Coord], count: int
                 ) -> list[tuple[Coord, ...]]:
    """Fallback candidate sets when no exact sub-torus placement is
    free: grow from each seed by nearest free chip (hop distance to
    the set, deterministic coord tiebreak)."""
    out: list[tuple[Coord, ...]] = []
    seen: set[frozenset[Coord]] = set()
    for seed in sorted(free):
        picked = [seed]
        pool = set(free)
        pool.discard(seed)
        while len(picked) < count and pool:
            best = min(
                pool,
                key=lambda c: (min(grid.hop_distance(c, p)
                                   for p in picked), c),
            )
            picked.append(best)
            pool.discard(best)
        if len(picked) == count:
            key = frozenset(picked)
            if key not in seen:
                seen.add(key)
                out.append(tuple(sorted(picked)))
    return out


def rank_placements(grid: TorusGrid, free_names: list[str], count: int,
                    penalties: dict[str, int] | None = None
                    ) -> list[list[str]]:
    """Candidate device sets for a ``count``-chip claim, best first.

    Only names with coordinates participate; an empty result means the
    caller should keep its first-fit order (no grid information, or
    count exceeds the coordinated free chips).

    ``penalties`` (device name -> headroom penalty,
    :func:`device_headroom_penalty`) is the power/thermal term: a
    placement touching a throttling / thermally-drifting / straggling
    chip ranks below every clean placement, but stays in the list --
    last resort, never excluded.
    """
    if count < 1:
        return []
    free = {grid.coords[n] for n in free_names if n in grid.coords}
    if len(free) < count:
        return []
    inventory = _free_placements(grid, free, _protected_shapes(grid))
    candidates: list[tuple[Coord, ...]] = []
    for shape in shapes_for_count(grid, count):
        for cells in placements(grid, shape):
            if all(c in free for c in cells):
                candidates.append(cells)
    if not candidates:
        candidates = _greedy_sets(grid, free, count)
    # One coord->name inversion for every candidate (cell_names would
    # rebuild it per placement).
    by_coord = {c: n for n, c in grid.coords.items()}
    penalties = penalties or {}
    scored = []
    for cells in candidates:
        cellset = set(cells)
        names = [by_coord.get(c) for c in cells]
        if None in names:
            continue  # a cell with no published device: not realizable
        max_hops, surface = set_compactness(grid, cellset)
        scored.append((
            sum(penalties.get(n, 0) for n in names),
            max_hops,
            frag_cost(cellset, inventory),
            surface,
            sorted(names),
            names,
        ))
    scored.sort(key=lambda t: t[:5])
    return [t[5] for t in scored]


def order_candidates(grid: TorusGrid, free_names: list[str], count: int,
                     penalties: dict[str, int] | None = None
                     ) -> list[str] | None:
    """A full preference ordering of ``free_names`` for a backtracking
    allocator: the best-ranked placement's devices first, then each
    next placement's unseen devices, then any remaining names in their
    original (first-fit) order. None = no topology signal; keep the
    caller's order. ``penalties`` biases the ranking away from
    degraded chips (see :func:`rank_placements`)."""
    ranked = rank_placements(grid, free_names, count,
                             penalties=penalties)
    if not ranked:
        return None
    ordered: list[str] = []
    seen: set[str] = set()
    for names in ranked:
        for name in names:
            if name not in seen:
                seen.add(name)
                ordered.append(name)
    for name in free_names:
        if name not in seen:
            seen.add(name)
            ordered.append(name)
    return ordered
