"""Placement simulator: claim churn against v5e/v5p-shaped grids.

Replays a randomized-but-deterministic claim arrival/departure trace
against a slice grid twice -- once with the scheduler's historical
first-fit policy, once with the topology scorer -- and reports
fragmentation over time: fragmentation score, allocatable largest
shape, allocation compactness. The SAME trace drives both policies
(sizes/lifetimes are pre-drawn from the seed), so the comparison is
paired, not statistical.

This is the `bench.py --placement-sim` engine and the fixture behind
the tier-1 placement smoke test.
"""

from __future__ import annotations

import random
import statistics
from dataclasses import dataclass

from .grid import TorusGrid, default_wrap
from .score import (
    frag_from_largest,
    largest_free_shape,
    rank_placements,
    set_compactness,
)

# Typical TPU claim sizes: single chips up to half-slice blocks.
DEFAULT_SIZES = (1, 1, 2, 4, 4, 8)


def grid_for_type(accelerator_type: str) -> TorusGrid:
    """A fully-populated TorusGrid for an accelerator type string
    (e.g. ``v5e-16``, ``v5p-32``), chips named ``chip-<i>`` in
    row-major publication order."""
    from ...tpulib.binding import (  # noqa: PLC0415 - leaf dependency
        _parse_type,
        _slice_shape,
    )

    parsed = _parse_type(accelerator_type)
    if parsed is None:
        raise ValueError(f"unknown accelerator type {accelerator_type!r}")
    gen, chips = parsed
    dims = _slice_shape(gen, chips)
    coords = {}
    i = 0
    for z in range(dims[2]):
        for y in range(dims[1]):
            for x in range(dims[0]):
                coords[f"chip-{i}"] = (x, y, z)
                i += 1
    return TorusGrid(dims=dims, wrap=default_wrap(gen.name, dims),
                     coords=coords)


@dataclass(frozen=True)
class TraceEvent:
    """One simulator step: an optional arrival (size, lifetime)."""

    size: int  # 0 = no arrival this step
    lifetime: int


def make_trace(steps: int, seed: int, sizes=DEFAULT_SIZES,
               arrival_prob: float = 0.7, max_lifetime: int = 25
               ) -> list[TraceEvent]:
    """The deterministic churn trace both policies replay."""
    rng = random.Random(seed)
    out = []
    for _ in range(steps):
        if rng.random() < arrival_prob:
            out.append(TraceEvent(size=rng.choice(sizes),
                                  lifetime=rng.randint(1, max_lifetime)))
        else:
            out.append(TraceEvent(size=0, lifetime=0))
    return out


def _first_fit_pick(grid: TorusGrid, free_names: list[str], size: int
                    ) -> list[str] | None:
    """The pre-topology scheduler policy: first ``size`` free devices
    in publication order, scattered or not."""
    if len(free_names) < size:
        return None
    return free_names[:size]


def _scored_pick(grid: TorusGrid, free_names: list[str], size: int
                 ) -> list[str] | None:
    ranked = rank_placements(grid, free_names, size)
    if ranked:
        return ranked[0]
    return _first_fit_pick(grid, free_names, size)


_POLICIES = {"first_fit": _first_fit_pick, "scored": _scored_pick}


def simulate_churn(grid: TorusGrid, trace: list[TraceEvent],
                   policy: str = "scored", metrics=None,
                   pool: str = "sim") -> dict:
    """Replay ``trace`` under ``policy``; returns the fragmentation /
    compactness summary. ``metrics`` (a ``PlacementMetrics``) gets the
    per-step gauges + per-allocation compactness observations, proving
    the exporter wiring end to end."""
    pick = _POLICIES[policy]
    all_names = sorted(grid.coords, key=lambda n: (len(n), n))
    allocated: dict[int, tuple[list[str], int]] = {}  # id -> (devs, expiry)
    next_id = 0
    frag_series: list[float] = []
    largest_series: list[int] = []
    hops: list[int] = []
    failed = 0
    for step, ev in enumerate(trace):
        for cid in [c for c, (_, exp) in allocated.items() if exp <= step]:
            del allocated[cid]
        taken = {d for devs, _ in allocated.values() for d in devs}
        free_names = [n for n in all_names if n not in taken]
        if ev.size:
            devs = pick(grid, free_names, ev.size)
            if devs is None:
                failed += 1
            else:
                allocated[next_id] = (devs, step + ev.lifetime)
                next_id += 1
                cells = {grid.coords[d] for d in devs}
                max_hops, _ = set_compactness(grid, cells)
                hops.append(max_hops)
                if metrics is not None:
                    metrics.compactness.labels(pool).observe(max_hops)
                taken |= set(devs)
                free_names = [n for n in all_names if n not in taken]
        free = {grid.coords[n] for n in free_names}
        # One sweep per step: frag is derived from the same
        # largest-shape result instead of recomputing it.
        _, chips = largest_free_shape(grid, free)
        frag = frag_from_largest(chips, len(free))
        frag_series.append(frag)
        largest_series.append(chips)
        if metrics is not None:
            metrics.frag_score.labels(pool).set(frag)
            metrics.largest_shape.labels(pool).set(chips)
    return {
        "frag_mean": round(statistics.fmean(frag_series), 4),
        "frag_max": round(max(frag_series), 4),
        "frag_final": round(frag_series[-1], 4),
        "largest_shape_mean_chips": round(
            statistics.fmean(largest_series), 2),
        "largest_shape_min_chips": min(largest_series),
        "compactness_mean_hops": round(statistics.fmean(hops), 3)
        if hops else 0.0,
        "compactness_max_hops": max(hops) if hops else 0,
        "allocs": len(hops),
        "alloc_failures": failed,
    }


def run_placement_bench(topologies=("v5e-16", "v5p-32"), steps: int = 400,
                        seed: int = 20260802, metrics=None) -> dict:
    """First-fit vs. scored on the same trace per topology; the
    structure bench.py flattens into its extras."""
    out: dict = {}
    for topo in topologies:
        grid = grid_for_type(topo)
        trace = make_trace(steps, seed)
        out[topo] = {
            policy: simulate_churn(
                grid, trace, policy=policy, metrics=metrics,
                pool=f"{topo}/{policy}")
            for policy in ("first_fit", "scored")
        }
    return out
