"""Placement simulator: claim churn against v5e/v5p-shaped grids.

Replays a randomized-but-deterministic claim arrival/departure trace
against a slice grid twice -- once with the scheduler's historical
first-fit policy, once with the topology scorer -- and reports
fragmentation over time: fragmentation score, allocatable largest
shape, allocation compactness. The SAME trace drives both policies
(sizes/lifetimes are pre-drawn from the seed), so the comparison is
paired, not statistical.

This is the `bench.py --placement-sim` engine and the fixture behind
the tier-1 placement smoke test.
"""

from __future__ import annotations

import random
import statistics
from dataclasses import dataclass

from .grid import Coord, TorusGrid, default_wrap
from .score import (
    _greedy_sets,
    frag_from_largest,
    largest_free_shape,
    rank_placements,
    set_compactness,
)
from .shapes import enumerate_shapes, placements, shapes_for_count

# Typical TPU claim sizes: single chips up to half-slice blocks.
DEFAULT_SIZES = (1, 1, 2, 4, 4, 8)


def grid_for_type(accelerator_type: str) -> TorusGrid:
    """A fully-populated TorusGrid for an accelerator type string
    (e.g. ``v5e-16``, ``v5p-32``), chips named ``chip-<i>`` in
    row-major publication order."""
    from ...tpulib.binding import (  # noqa: PLC0415 - leaf dependency
        _parse_type,
        _slice_shape,
    )

    parsed = _parse_type(accelerator_type)
    if parsed is None:
        raise ValueError(f"unknown accelerator type {accelerator_type!r}")
    gen, chips = parsed
    dims = _slice_shape(gen, chips)
    coords = {}
    i = 0
    for z in range(dims[2]):
        for y in range(dims[1]):
            for x in range(dims[0]):
                coords[f"chip-{i}"] = (x, y, z)
                i += 1
    return TorusGrid(dims=dims, wrap=default_wrap(gen.name, dims),
                     coords=coords)


@dataclass(frozen=True)
class TraceEvent:
    """One simulator step: an optional arrival (size, lifetime)."""

    size: int  # 0 = no arrival this step
    lifetime: int


def make_trace(steps: int, seed: int, sizes=DEFAULT_SIZES,
               arrival_prob: float = 0.7, max_lifetime: int = 25
               ) -> list[TraceEvent]:
    """The deterministic churn trace both policies replay."""
    rng = random.Random(seed)
    out = []
    for _ in range(steps):
        if rng.random() < arrival_prob:
            out.append(TraceEvent(size=rng.choice(sizes),
                                  lifetime=rng.randint(1, max_lifetime)))
        else:
            out.append(TraceEvent(size=0, lifetime=0))
    return out


def _first_fit_pick(grid: TorusGrid, free_names: list[str], size: int
                    ) -> list[str] | None:
    """The pre-topology scheduler policy: first ``size`` free devices
    in publication order, scattered or not."""
    if len(free_names) < size:
        return None
    return free_names[:size]


def _scored_pick(grid: TorusGrid, free_names: list[str], size: int
                 ) -> list[str] | None:
    ranked = rank_placements(grid, free_names, size)
    if ranked:
        return ranked[0]
    return _first_fit_pick(grid, free_names, size)


_POLICIES = {"first_fit": _first_fit_pick, "scored": _scored_pick}


def simulate_churn(grid: TorusGrid, trace: list[TraceEvent],
                   policy: str = "scored", metrics=None,
                   pool: str = "sim") -> dict:
    """Replay ``trace`` under ``policy``; returns the fragmentation /
    compactness summary. ``metrics`` (a ``PlacementMetrics``) gets the
    per-step gauges + per-allocation compactness observations, proving
    the exporter wiring end to end."""
    pick = _POLICIES[policy]
    all_names = sorted(grid.coords, key=lambda n: (len(n), n))
    allocated: dict[int, tuple[list[str], int]] = {}  # id -> (devs, expiry)
    next_id = 0
    frag_series: list[float] = []
    largest_series: list[int] = []
    hops: list[int] = []
    failed = 0
    for step, ev in enumerate(trace):
        for cid in [c for c, (_, exp) in allocated.items() if exp <= step]:
            del allocated[cid]
        taken = {d for devs, _ in allocated.values() for d in devs}
        free_names = [n for n in all_names if n not in taken]
        if ev.size:
            devs = pick(grid, free_names, ev.size)
            if devs is None:
                failed += 1
            else:
                allocated[next_id] = (devs, step + ev.lifetime)
                next_id += 1
                cells = {grid.coords[d] for d in devs}
                max_hops, _ = set_compactness(grid, cells)
                hops.append(max_hops)
                if metrics is not None:
                    metrics.compactness.labels(pool).observe(max_hops)
                taken |= set(devs)
                free_names = [n for n in all_names if n not in taken]
        free = {grid.coords[n] for n in free_names}
        # One sweep per step: frag is derived from the same
        # largest-shape result instead of recomputing it.
        _, chips = largest_free_shape(grid, free)
        frag = frag_from_largest(chips, len(free))
        frag_series.append(frag)
        largest_series.append(chips)
        if metrics is not None:
            metrics.frag_score.labels(pool).set(frag)
            metrics.largest_shape.labels(pool).set(chips)
    return {
        "frag_mean": round(statistics.fmean(frag_series), 4),
        "frag_max": round(max(frag_series), 4),
        "frag_final": round(frag_series[-1], 4),
        "largest_shape_mean_chips": round(
            statistics.fmean(largest_series), 2),
        "largest_shape_min_chips": min(largest_series),
        "compactness_mean_hops": round(statistics.fmean(hops), 3)
        if hops else 0.0,
        "compactness_max_hops": max(hops) if hops else 0,
        "allocs": len(hops),
        "alloc_failures": failed,
    }


# -- simulated re-pack (the defrag controller's what-if engine) ---------------


@dataclass(frozen=True)
class RepackMove:
    """One planned relocation: ``claim`` vacates ``cells`` and re-lands
    on ``target`` (both in grid coordinates)."""

    claim: str
    cells: tuple[Coord, ...]
    target: tuple[Coord, ...]


@dataclass(frozen=True)
class RepackPlan:
    """A feasible carve: relocate ``moves`` and the ``goal_shape``
    sub-torus at ``goal_cells`` becomes fully free. ``chips_before`` /
    ``chips_after`` are the largest-free-shape sizes the plan trades
    between (the frag-recovered signal)."""

    moves: tuple[RepackMove, ...]
    goal_shape: tuple[int, int, int]
    goal_cells: frozenset[Coord]
    chips_before: int
    chips_after: int


def _same_node(cells, node_of) -> bool:
    """A relocated claim must land on ONE node: allocation fits per
    node (pkg/scheduler._fit_on_node), so a cross-node destination
    could never actually be committed."""
    if node_of is None:
        return True
    nodes = {node_of.get(c) for c in cells}
    return len(nodes) == 1 and None not in nodes


def _place_displaced(grid: TorusGrid, avail: set[Coord], size: int,
                     node_of=None) -> tuple[Coord, ...] | None:
    """Destination cells for one displaced claim: the most compact
    exact sub-torus placement fully inside ``avail``, falling back to
    a greedy nearest-neighbor set when no box fits."""
    for shape in shapes_for_count(grid, size):
        for cells in placements(grid, shape):
            if all(c in avail for c in cells) and \
                    _same_node(cells, node_of):
                return cells
    for cells in _greedy_sets(grid, avail, size):
        if _same_node(cells, node_of):
            return cells
    return None


def plan_repack(grid: TorusGrid, free: set[Coord],
                allocations: dict[str, set[Coord]],
                movable=None, cost_fn=None, max_moves: int | None = None,
                node_of: dict[Coord, str] | None = None
                ) -> RepackPlan | None:
    """Simulated re-pack: the largest sub-torus shape that can be made
    fully free by relocating at most ``max_moves`` movable claims into
    the remaining free space, and the cheapest way to do it.

    The search walks the protected-shape catalog largest volume first;
    for each placement of a shape it collects the claims squatting on
    it, verifies every one is ``movable`` and re-placeable in the
    space left over, and scores the displacement with ``cost_fn``
    (claim ids -> float; defaults to the claim count). Among feasible
    carves of the winning volume the cheapest (then fewest chips
    moved, then deterministic anchor order) wins -- the 2502.01909
    multi-objective trade: frag recovered vs. migration cost, with
    gang disruption and claim age folded in by the caller's cost_fn.

    Returns None when no shape larger than the current largest free
    shape can be carved within the move budget.
    """
    free = set(free)
    movable = movable if movable is not None else (lambda cid: True)
    cost_fn = cost_fn if cost_fn is not None else \
        (lambda cids: float(len(cids)))
    _, chips_before = largest_free_shape(grid, free)
    cell_owner: dict[Coord, str] = {}
    for cid, cells in allocations.items():
        for c in cells:
            cell_owner[c] = cid
    movable_chips = sum(len(cells) for cid, cells in allocations.items()
                        if movable(cid))
    best: tuple | None = None  # (volume, (cost, moved, cells), shape,
    #                             cells, targets)
    for shape in enumerate_shapes(
            grid, max_chips=len(free) + movable_chips):
        vol = shape[0] * shape[1] * shape[2]
        if vol <= chips_before:
            break  # volume-descending: no gain left below this
        if best is not None and vol < best[0]:
            break  # every shape of the winning volume already judged
        for cells in placements(grid, shape):
            cellset = set(cells)
            if not all(c in free or c in cell_owner for c in cellset):
                continue  # overlaps a device the planner can't model
            owners = sorted({cell_owner[c] for c in cellset
                             if c in cell_owner})
            if not owners or any(not movable(o) for o in owners):
                continue
            if max_moves is not None and len(owners) > max_moves:
                continue
            displaced = set().union(*(allocations[o] for o in owners))
            avail = (free | displaced) - cellset
            targets: dict[str, tuple[Coord, ...]] = {}
            ok = True
            # Relocate biggest claims first: they need the contiguous
            # space the smaller ones would otherwise shred.
            for o in sorted(owners,
                            key=lambda o: (-len(allocations[o]), o)):
                dest = _place_displaced(grid, avail,
                                        len(allocations[o]), node_of)
                if dest is None:
                    ok = False
                    break
                targets[o] = dest
                avail -= set(dest)
            if not ok:
                continue
            key = (cost_fn(tuple(owners)),
                   sum(len(allocations[o]) for o in owners), cells)
            if best is None or vol > best[0] or \
                    (vol == best[0] and key < best[1]):
                best = (vol, key, shape, cells, targets)
    if best is None:
        return None
    _vol, _key, shape, cells, targets = best
    moves = tuple(
        RepackMove(claim=o, cells=tuple(sorted(allocations[o])),
                   target=targets[o])
        for o in sorted(targets))
    projected = (free | set().union(*(allocations[o] for o in targets))
                 ) - set().union(*(set(t) for t in targets.values()))
    _, chips_after = largest_free_shape(grid, projected)
    return RepackPlan(moves=moves, goal_shape=shape,
                      goal_cells=frozenset(cells),
                      chips_before=chips_before,
                      chips_after=chips_after)


def run_placement_bench(topologies=("v5e-16", "v5p-32"), steps: int = 400,
                        seed: int = 20260802, metrics=None) -> dict:
    """First-fit vs. scored on the same trace per topology; the
    structure bench.py flattens into its extras."""
    out: dict = {}
    for topo in topologies:
        grid = grid_for_type(topo)
        trace = make_trace(steps, seed)
        out[topo] = {
            policy: simulate_churn(
                grid, trace, policy=policy, metrics=metrics,
                pool=f"{topo}/{policy}")
            for policy in ("first_fit", "scored")
        }
    return out
