"""Shared small HTTP server (metrics, healthz, debug, ...)."""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

# handler() -> (status_code, content_type, body_bytes)
EndpointFn = Callable[[], tuple[int, str, bytes]]


class SimpleHTTPEndpoint:
    """Serves GET <path> from ``fn``; ``extra`` adds more path->fn
    routes on the same listener (e.g. /metrics + /debug/stacks).
    A route key ending in ``/*`` is a PREFIX route: its handler takes
    the rest of the path as one argument (e.g. ``/debug/claims/*`` ->
    ``fn("<uid>")``). Anything else 404s."""

    def __init__(self, path: str, fn: EndpointFn, host: str = "127.0.0.1",
                 port: int = 0, thread_name: str = "http-endpoint",
                 extra: dict[str, EndpointFn] | None = None):
        routes = {path.rstrip("/"): fn}
        prefix_routes: dict[str, Callable[[str],
                                          tuple[int, str, bytes]]] = {}
        for p, f in (extra or {}).items():
            if p.endswith("/*"):
                prefix_routes[p[:-2].rstrip("/")] = f
            else:
                routes[p.rstrip("/")] = f
        default = path.rstrip("/")

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                got = self.path.split("?", 1)[0].rstrip("/")
                # Exact route first ("" can be a registered root route);
                # a bare "/" falls back to the primary endpoint.
                handler = routes.get(got, routes.get(default)
                                     if got == "" else None)
                if handler is None:
                    for prefix, pfn in prefix_routes.items():
                        if got.startswith(prefix + "/"):
                            handler = (lambda pfn=pfn, rest=got[
                                len(prefix) + 1:]: pfn(rest))
                            break
                if handler is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                status, ctype, body = handler()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name=thread_name, daemon=True
        )

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
