"""List+watch cache with a uid index -- the client-go informer analog.

Reference: the CD kubelet plugin and controller consume CRs through
informers with local caches (cmd/compute-domain-kubelet-plugin/
computedomain.go:118-137, cmd/compute-domain-daemon/cdclique.go) instead
of re-listing per operation. This is the same shape over the in-tree
KubeClient: an initial list primes the cache, a streamed watch applies
incremental updates, and a periodic relist reconciles anything a watch
gap missed (required: the watch does not replay events lost across a
410, see KubeClient.watch).

Works against both clients:
- KubeClient: real `?watch=true` stream + timer-driven relist.
- FakeKubeClient: its resource-scoped watch hook; events are applied
  INCREMENTALLY (the fake delivers full post-merge objects, so the
  watch-event path handles them verbatim). Earlier builds relisted the
  whole store on every matching event, which turned one burst of N
  writes into N full lists -- the relist path now survives only as the
  conservative fallback for events without usable metadata, and
  concurrent relist requests coalesce into a single trailing relist
  per burst. ``relist_total`` (exported as
  ``tpu_dra_informer_relist_total`` by consumers wiring ``on_relist``)
  counts how often the expensive path actually runs.
"""

from __future__ import annotations

import heapq
import json
import logging
import os
import random
import threading
import time
from typing import Callable

logger = logging.getLogger(__name__)

# Relist priority (lower = first): the allocation-critical state
# (slices = the inventory, claims = the held allocations) must be
# fresh before anything else is worth scheduling against, so a restart
# storm drains those first; pods/daemonsets/jobs are derived work that
# tolerates a stale cache longest. Unlisted resources drain last.
RELIST_PRIORITY: dict[str, int] = {
    "resourceslices": 0,
    "resourceclaims": 1,
    "deviceclasses": 2,
    "resourceclaimtemplates": 2,
    "computedomains": 2,
    "partitionsets": 2,
    "nodes": 3,
    "pods": 4,
    "daemonsets": 5,
    "jobs": 5,
}


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class RelistCoordinator:
    """Shards full relists across a set of informers.

    A restart storm (apiserver bounce, watch-gap burst) used to fire
    all nine informers' relists at once -- nine concurrent full LISTs
    against an apiserver that just came back. Routed through this
    coordinator they instead drain as a bounded trickle:

    - **Concurrency cap** (``TPU_DRA_SCHED_RELIST_CONCURRENCY``,
      default 2): at most N relists in flight at once.
    - **Priority ordering** (:data:`RELIST_PRIORITY`): queued waiters
      are admitted slices/claims before pods/daemonsets, so the
      allocation-critical caches recover first.
    - **Per-resource jittered backoff**
      (``TPU_DRA_SCHED_RELIST_BASE_S`` doubling per consecutive
      relist up to ``TPU_DRA_SCHED_RELIST_MAX_S``, 50-100% decorrelated
      jitter, streak reset after ``TPU_DRA_SCHED_RELIST_QUIET_S`` of
      quiet): a resource whose watch keeps gapping backs off
      exponentially instead of hammering LIST in a tight loop. The
      applied delay is reported through ``on_backoff(resource,
      seconds)`` (exported as
      ``tpu_dra_informer_relist_backoff_seconds``).

    The first relist of a quiet resource (startup, an isolated gap)
    pays zero delay -- only *repeat* relists inside the quiet window
    back off."""

    def __init__(self, concurrency: int | None = None,
                 base_delay: float | None = None,
                 max_delay: float | None = None,
                 quiet_period: float | None = None,
                 on_backoff: Callable[[str, float], None] | None = None,
                 rng: random.Random | None = None,
                 time_fn: Callable[[], float] = time.monotonic,
                 sleep_fn: Callable[[float], None] = time.sleep):
        if concurrency is None:
            concurrency = int(_env_float(
                "TPU_DRA_SCHED_RELIST_CONCURRENCY", 2))
        self.concurrency = max(1, concurrency)
        self.base_delay = (base_delay if base_delay is not None else
                           _env_float("TPU_DRA_SCHED_RELIST_BASE_S", 0.5))
        self.max_delay = (max_delay if max_delay is not None else
                          _env_float("TPU_DRA_SCHED_RELIST_MAX_S", 30.0))
        self.quiet_period = (quiet_period if quiet_period is not None else
                             _env_float("TPU_DRA_SCHED_RELIST_QUIET_S",
                                        60.0))
        self._on_backoff = on_backoff
        self._rng = rng if rng is not None else random.Random()
        self._time = time_fn
        self._sleep = sleep_fn
        self._cv = threading.Condition()
        self._active = 0
        self._seq = 0
        self._waiting: list[tuple[int, int, object]] = []
        self._streak: dict[str, int] = {}
        self._last: dict[str, float] = {}

    def backoff_for(self, resource: str) -> float:
        """Advance the resource's streak and return the jittered delay
        to apply before its next relist (0 for a quiet resource)."""
        with self._cv:
            now = self._time()
            last = self._last.get(resource)
            if last is not None and now - last < self.quiet_period:
                self._streak[resource] = self._streak.get(resource, 0) + 1
            else:
                self._streak[resource] = 0
            n = self._streak[resource]
            if n <= 0:
                return 0.0
            delay = min(self.base_delay * (2 ** (n - 1)), self.max_delay)
            return delay * (0.5 + self._rng.random() * 0.5)

    def run(self, resource: str, fn: Callable[[], None]) -> None:
        """Apply the resource's backoff, then run ``fn`` inside the
        priority-ordered concurrency gate."""
        delay = self.backoff_for(resource)
        if delay > 0:
            if self._on_backoff is not None:
                try:
                    self._on_backoff(resource, delay)
                except Exception:  # noqa: BLE001 - metrics hook
                    logger.exception("relist backoff hook failed")
            self._sleep(delay)
        pri = RELIST_PRIORITY.get(resource, 9)
        token = object()
        with self._cv:
            self._seq += 1
            heapq.heappush(self._waiting, (pri, self._seq, token))
            while self._active >= self.concurrency or \
                    self._waiting[0][2] is not token:
                self._cv.wait(timeout=5.0)
            heapq.heappop(self._waiting)
            self._active += 1
            # Wake the next head: with free slots it may run NOW,
            # concurrently with us.
            self._cv.notify_all()
        try:
            fn()
        finally:
            with self._cv:
                self._active -= 1
                self._last[resource] = self._time()
                self._cv.notify_all()


class Informer:
    def __init__(
        self,
        kube,
        group: str,
        version: str,
        resource: str,
        kind: str,
        namespace: str | None = None,
        resync_period: float = 30.0,
        on_relist: Callable[[], None] | None = None,
        coordinator: RelistCoordinator | None = None,
    ):
        self.kube = kube
        self.group = group
        self.version = version
        self.resource = resource
        self.kind = kind
        self.namespace = namespace
        self.resync_period = resync_period
        self._lock = threading.Lock()
        self._cache: dict[tuple[str, str], dict] = {}  # (ns, name) -> obj
        self._by_uid: dict[str, tuple[str, str]] = {}
        self._hooks: list[Callable[[], None]] = []
        self._event_hooks: list[Callable[[str, dict], None]] = []
        self._stop = threading.Event()
        self._synced = threading.Event()
        self._started = False
        # Relist accounting + burst coalescing: while one relist runs,
        # further requests just mark it pending; ONE trailing relist
        # covers the whole burst.
        self.relist_total = 0
        self._on_relist = on_relist
        # Optional RelistCoordinator: full relists then queue through
        # the shared priority/concurrency/backoff gate instead of
        # hitting the apiserver immediately (restart-storm discipline).
        self._coordinator = coordinator
        self._relist_lock = threading.Lock()
        self._relist_active = False
        self._relist_pending = False
        self._fake_hook = None
        # Modelable delivery seam (pkg/analysis/modelcheck.py): when
        # set, ``event_gate(ev_type, obj) -> bool`` is consulted before
        # each watch event is applied to the cache. True applies now;
        # False parks the event on an internal queue until
        # ``flush_deferred()`` -- which is how the model checker turns
        # "informer lag" into an explicit interleaving choice instead
        # of a wall-clock accident. None (production) applies
        # immediately, zero overhead.
        self.event_gate: Callable[[str, dict], bool] | None = None
        self._deferred: list[tuple[str, dict]] = []

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "Informer":
        if self._started:
            return self
        self._started = True
        try:
            self.relist()
        except Exception:  # noqa: BLE001 - transient API failure at boot
            # Tolerated: the watch + resync loop converge once the API
            # server answers; consumers see an empty cache until then
            # (RetryableError semantics), never a crashed constructor.
            logger.exception("initial informer list failed; will resync")
        if hasattr(self.kube, "add_resource_watcher"):  # FakeKubeClient
            self._fake_hook = self._on_fake_resource_event
            self.kube.add_resource_watcher(self._fake_hook)
        elif hasattr(self.kube, "add_watcher"):  # legacy fake surface
            self.kube.add_watcher(self._on_fake_event)
        else:
            self.kube.watch(
                self.group, self.version, self.resource,
                self._on_watch_event,
                namespace=self.namespace, stop=self._stop,
                # Watch-gap (410 Gone / ERROR event): events from the
                # gap are never replayed, so relist NOW instead of
                # serving a stale cache until the next periodic resync.
                on_gap=self._relist_on_gap,
            )
            t = threading.Thread(
                target=self._resync_loop,
                name=f"informer-resync-{self.resource}", daemon=True,
            )
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._fake_hook is not None and hasattr(
                self.kube, "remove_resource_watcher"):
            self.kube.remove_resource_watcher(self._fake_hook)
            self._fake_hook = None

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    # -- event plumbing -------------------------------------------------------

    def add_change_hook(self, fn: Callable[[], None]) -> None:
        """fn() fires after any cache change (coalesced, no payload --
        consumers re-read the cache, informer-handler style)."""
        self._hooks.append(fn)

    def add_event_hook(self, fn: Callable[[str, dict], None]) -> None:
        """fn(ev_type, obj) fires once per changed OBJECT (watch events
        and relist diffs alike) -- the payload-carrying feed a keyed
        workqueue consumer needs to stay O(changes)."""
        self._event_hooks.append(fn)

    def _fire(self) -> None:
        for fn in list(self._hooks):
            try:
                fn()
            except Exception:  # noqa: BLE001 - consumer bug must not kill us
                logger.exception("informer change hook failed")

    def _fire_events(self, events: list[tuple[str, dict]]) -> None:
        for fn in list(self._event_hooks):
            for ev_type, obj in events:
                try:
                    fn(ev_type, obj)
                except Exception:  # noqa: BLE001 - consumer bug
                    logger.exception("informer event hook failed")

    def _key(self, obj: dict) -> tuple[str, str]:
        md = obj.get("metadata", {})
        return (md.get("namespace", ""), md.get("name", ""))

    def _on_watch_event(self, ev_type: str, obj: dict) -> None:
        gate = self.event_gate
        if gate is not None:
            try:
                deliver = gate(ev_type, obj)
            except Exception:  # noqa: BLE001 - gate bug must not lose events
                logger.exception("informer event gate failed; delivering")
                deliver = True
            if not deliver:
                with self._lock:
                    self._deferred.append((ev_type, obj))
                return
        self._apply_event(ev_type, obj)

    def flush_deferred(self) -> int:
        """Apply every event the gate parked, in arrival order; returns
        how many were applied. No-op (0) without a gate."""
        with self._lock:
            pending, self._deferred = self._deferred, []
        for ev_type, obj in pending:
            self._apply_event(ev_type, obj)
        return len(pending)

    def _apply_event(self, ev_type: str, obj: dict) -> None:
        changed = False
        with self._lock:
            key = self._key(obj)
            uid = obj.get("metadata", {}).get("uid", "")
            if ev_type == "DELETED":
                changed = self._cache.pop(key, None) is not None
                if uid:
                    self._by_uid.pop(uid, None)
            else:
                old = self._cache.get(key)
                changed = old != obj
                self._cache[key] = obj
                if uid:
                    self._by_uid[uid] = key
        if changed:
            self._fire_events([(ev_type, obj)])
            self._fire()

    def _on_fake_resource_event(self, group: str, resource: str,
                                namespace: str, ev_type: str,
                                obj: dict) -> None:
        """Resource-scoped FakeKubeClient events apply incrementally:
        exact (group, resource) match, full post-merge objects -- no
        kind guessing, no relist."""
        if self._stop.is_set():
            return
        if group != self.group or resource != self.resource:
            return
        if self.namespace and namespace != self.namespace:
            return
        if not obj.get("metadata", {}).get("name"):
            self.relist()  # unusable payload: conservative fallback
            return
        self._synced.set()
        # The fake store may later mutate this very dict in place (its
        # ADDED payload is the stored object): cache a private copy so
        # change detection compares against what was actually seen.
        self._on_watch_event(ev_type, json.loads(json.dumps(obj)))

    def _on_fake_event(self, ev_type: str, obj: dict) -> None:
        """Legacy global-watcher surface (fakes without resource-scoped
        hooks): filter by kind and relist -- events for other kinds
        can't be told apart reliably, so the conservative path stays."""
        if self._stop.is_set():
            return
        if obj.get("kind") not in (self.kind, None):
            return
        self.relist()

    def _relist_on_gap(self) -> None:
        if self._stop.is_set():
            return
        try:
            self.relist()
        except Exception:  # noqa: BLE001 - the resync loop converges
            logger.exception("relist after watch gap failed")

    def _resync_loop(self) -> None:
        while not self._stop.wait(self.resync_period):
            try:
                self.relist()
            except Exception:  # noqa: BLE001 - transient API failures
                logger.exception("informer relist failed")

    def relist(self) -> None:
        """Full list + cache swap. Concurrent requests coalesce: while
        one relist is in flight, any number of further requests fold
        into a single trailing relist (one per drained burst)."""
        with self._relist_lock:
            if self._relist_active:
                self._relist_pending = True
                return
            self._relist_active = True
        try:
            while True:
                if self._coordinator is not None:
                    self._coordinator.run(self.resource,
                                          self._relist_once)
                else:
                    self._relist_once()
                with self._relist_lock:
                    if not self._relist_pending:
                        return
                    self._relist_pending = False
        finally:
            with self._relist_lock:
                self._relist_active = False

    def _relist_once(self) -> None:
        self.relist_total += 1
        if self._on_relist is not None:
            try:
                self._on_relist()
            except Exception:  # noqa: BLE001 - metrics hook
                logger.exception("informer relist hook failed")
        items = self.kube.list(
            self.group, self.version, self.resource,
            namespace=self.namespace,
        )
        with self._lock:
            old = self._cache
            self._cache = {self._key(o): o for o in items}
            self._by_uid = {
                o["metadata"]["uid"]: self._key(o)
                for o in items
                if o.get("metadata", {}).get("uid")
            }
            changed = old != self._cache
            events: list[tuple[str, dict]] = []
            if changed and self._event_hooks:
                for key, obj in self._cache.items():
                    if old.get(key) != obj:
                        ev = "MODIFIED" if key in old else "ADDED"
                        events.append((ev, obj))
                for key, obj in old.items():
                    if key not in self._cache:
                        events.append(("DELETED", obj))
        self._synced.set()
        if changed:
            self._fire_events(events)
            self._fire()

    # -- cache reads ----------------------------------------------------------

    def get_by_uid(self, uid: str) -> dict | None:
        with self._lock:
            key = self._by_uid.get(uid)
            obj = self._cache.get(key) if key else None
            # A delete+recreate under the same (ns, name) during a watch
            # gap leaves the old uid pointing at the new object until the
            # next resync -- never serve an object whose uid differs.
            if obj is not None and obj.get("metadata", {}).get("uid") != uid:
                return None
            return obj

    def get(self, name: str, namespace: str = "") -> dict | None:
        with self._lock:
            return self._cache.get((namespace, name))

    def list(self) -> list[dict]:
        with self._lock:
            return list(self._cache.values())
