"""List+watch cache with a uid index -- the client-go informer analog.

Reference: the CD kubelet plugin and controller consume CRs through
informers with local caches (cmd/compute-domain-kubelet-plugin/
computedomain.go:118-137, cmd/compute-domain-daemon/cdclique.go) instead
of re-listing per operation. This is the same shape over the in-tree
KubeClient: an initial list primes the cache, a streamed watch applies
incremental updates, and a periodic relist reconciles anything a watch
gap missed (required: the watch does not replay events lost across a
410, see KubeClient.watch).

Works against both clients:
- KubeClient: real `?watch=true` stream + timer-driven relist.
- FakeKubeClient: its global watch hook; events for other resources are
  filtered by `kind`, and each matching event triggers a relist (the
  fake store is tiny, and relisting sidesteps incremental bookkeeping
  differences between patch/update notification shapes).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable

logger = logging.getLogger(__name__)


class Informer:
    def __init__(
        self,
        kube,
        group: str,
        version: str,
        resource: str,
        kind: str,
        namespace: str | None = None,
        resync_period: float = 30.0,
    ):
        self.kube = kube
        self.group = group
        self.version = version
        self.resource = resource
        self.kind = kind
        self.namespace = namespace
        self.resync_period = resync_period
        self._lock = threading.Lock()
        self._cache: dict[tuple[str, str], dict] = {}  # (ns, name) -> obj
        self._by_uid: dict[str, tuple[str, str]] = {}
        self._hooks: list[Callable[[], None]] = []
        self._stop = threading.Event()
        self._synced = threading.Event()
        self._started = False

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "Informer":
        if self._started:
            return self
        self._started = True
        try:
            self.relist()
        except Exception:  # noqa: BLE001 - transient API failure at boot
            # Tolerated: the watch + resync loop converge once the API
            # server answers; consumers see an empty cache until then
            # (RetryableError semantics), never a crashed constructor.
            logger.exception("initial informer list failed; will resync")
        if hasattr(self.kube, "add_watcher"):  # FakeKubeClient
            self.kube.add_watcher(self._on_fake_event)
        else:
            self.kube.watch(
                self.group, self.version, self.resource,
                self._on_watch_event,
                namespace=self.namespace, stop=self._stop,
                # Watch-gap (410 Gone / ERROR event): events from the
                # gap are never replayed, so relist NOW instead of
                # serving a stale cache until the next periodic resync.
                on_gap=self._relist_on_gap,
            )
            t = threading.Thread(
                target=self._resync_loop,
                name=f"informer-resync-{self.resource}", daemon=True,
            )
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    # -- event plumbing -------------------------------------------------------

    def add_change_hook(self, fn: Callable[[], None]) -> None:
        """fn() fires after any cache change (coalesced, no payload --
        consumers re-read the cache, informer-handler style)."""
        self._hooks.append(fn)

    def _fire(self) -> None:
        for fn in list(self._hooks):
            try:
                fn()
            except Exception:  # noqa: BLE001 - consumer bug must not kill us
                logger.exception("informer change hook failed")

    def _key(self, obj: dict) -> tuple[str, str]:
        md = obj.get("metadata", {})
        return (md.get("namespace", ""), md.get("name", ""))

    def _on_watch_event(self, ev_type: str, obj: dict) -> None:
        changed = False
        with self._lock:
            key = self._key(obj)
            uid = obj.get("metadata", {}).get("uid", "")
            if ev_type == "DELETED":
                changed = self._cache.pop(key, None) is not None
                if uid:
                    self._by_uid.pop(uid, None)
            else:
                old = self._cache.get(key)
                changed = old != obj
                self._cache[key] = obj
                if uid:
                    self._by_uid[uid] = key
        if changed:
            self._fire()

    def _on_fake_event(self, ev_type: str, obj: dict) -> None:
        if self._stop.is_set():
            return  # FakeKubeClient has no watcher-removal path
        # Objects in the fake store usually carry their kind; ones that
        # don't (bare test fixtures) relist conservatively.
        if obj.get("kind") not in (self.kind, None):
            return
        self.relist()

    def _relist_on_gap(self) -> None:
        if self._stop.is_set():
            return
        try:
            self.relist()
        except Exception:  # noqa: BLE001 - the resync loop converges
            logger.exception("relist after watch gap failed")

    def _resync_loop(self) -> None:
        while not self._stop.wait(self.resync_period):
            try:
                self.relist()
            except Exception:  # noqa: BLE001 - transient API failures
                logger.exception("informer relist failed")

    def relist(self) -> None:
        items = self.kube.list(
            self.group, self.version, self.resource,
            namespace=self.namespace,
        )
        with self._lock:
            old = self._cache
            self._cache = {self._key(o): o for o in items}
            self._by_uid = {
                o["metadata"]["uid"]: self._key(o)
                for o in items
                if o.get("metadata", {}).get("uid")
            }
            changed = old != self._cache
        self._synced.set()
        if changed:
            self._fire()

    # -- cache reads ----------------------------------------------------------

    def get_by_uid(self, uid: str) -> dict | None:
        with self._lock:
            key = self._by_uid.get(uid)
            obj = self._cache.get(key) if key else None
            # A delete+recreate under the same (ns, name) during a watch
            # gap leaves the old uid pointing at the new object until the
            # next resync -- never serve an object whose uid differs.
            if obj is not None and obj.get("metadata", {}).get("uid") != uid:
                return None
            return obj

    def get(self, name: str, namespace: str = "") -> dict | None:
        with self._lock:
            return self._cache.get((namespace, name))

    def list(self) -> list[dict]:
        with self._lock:
            return list(self._cache.values())
