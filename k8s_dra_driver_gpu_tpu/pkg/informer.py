"""List+watch cache with a uid index -- the client-go informer analog.

Reference: the CD kubelet plugin and controller consume CRs through
informers with local caches (cmd/compute-domain-kubelet-plugin/
computedomain.go:118-137, cmd/compute-domain-daemon/cdclique.go) instead
of re-listing per operation. This is the same shape over the in-tree
KubeClient: an initial list primes the cache, a streamed watch applies
incremental updates, and a periodic relist reconciles anything a watch
gap missed (required: the watch does not replay events lost across a
410, see KubeClient.watch).

Works against both clients:
- KubeClient: real `?watch=true` stream + timer-driven relist.
- FakeKubeClient: its resource-scoped watch hook; events are applied
  INCREMENTALLY (the fake delivers full post-merge objects, so the
  watch-event path handles them verbatim). Earlier builds relisted the
  whole store on every matching event, which turned one burst of N
  writes into N full lists -- the relist path now survives only as the
  conservative fallback for events without usable metadata, and
  concurrent relist requests coalesce into a single trailing relist
  per burst. ``relist_total`` (exported as
  ``tpu_dra_informer_relist_total`` by consumers wiring ``on_relist``)
  counts how often the expensive path actually runs.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Callable

logger = logging.getLogger(__name__)


class Informer:
    def __init__(
        self,
        kube,
        group: str,
        version: str,
        resource: str,
        kind: str,
        namespace: str | None = None,
        resync_period: float = 30.0,
        on_relist: Callable[[], None] | None = None,
    ):
        self.kube = kube
        self.group = group
        self.version = version
        self.resource = resource
        self.kind = kind
        self.namespace = namespace
        self.resync_period = resync_period
        self._lock = threading.Lock()
        self._cache: dict[tuple[str, str], dict] = {}  # (ns, name) -> obj
        self._by_uid: dict[str, tuple[str, str]] = {}
        self._hooks: list[Callable[[], None]] = []
        self._event_hooks: list[Callable[[str, dict], None]] = []
        self._stop = threading.Event()
        self._synced = threading.Event()
        self._started = False
        # Relist accounting + burst coalescing: while one relist runs,
        # further requests just mark it pending; ONE trailing relist
        # covers the whole burst.
        self.relist_total = 0
        self._on_relist = on_relist
        self._relist_lock = threading.Lock()
        self._relist_active = False
        self._relist_pending = False
        self._fake_hook = None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "Informer":
        if self._started:
            return self
        self._started = True
        try:
            self.relist()
        except Exception:  # noqa: BLE001 - transient API failure at boot
            # Tolerated: the watch + resync loop converge once the API
            # server answers; consumers see an empty cache until then
            # (RetryableError semantics), never a crashed constructor.
            logger.exception("initial informer list failed; will resync")
        if hasattr(self.kube, "add_resource_watcher"):  # FakeKubeClient
            self._fake_hook = self._on_fake_resource_event
            self.kube.add_resource_watcher(self._fake_hook)
        elif hasattr(self.kube, "add_watcher"):  # legacy fake surface
            self.kube.add_watcher(self._on_fake_event)
        else:
            self.kube.watch(
                self.group, self.version, self.resource,
                self._on_watch_event,
                namespace=self.namespace, stop=self._stop,
                # Watch-gap (410 Gone / ERROR event): events from the
                # gap are never replayed, so relist NOW instead of
                # serving a stale cache until the next periodic resync.
                on_gap=self._relist_on_gap,
            )
            t = threading.Thread(
                target=self._resync_loop,
                name=f"informer-resync-{self.resource}", daemon=True,
            )
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._fake_hook is not None and hasattr(
                self.kube, "remove_resource_watcher"):
            self.kube.remove_resource_watcher(self._fake_hook)
            self._fake_hook = None

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    # -- event plumbing -------------------------------------------------------

    def add_change_hook(self, fn: Callable[[], None]) -> None:
        """fn() fires after any cache change (coalesced, no payload --
        consumers re-read the cache, informer-handler style)."""
        self._hooks.append(fn)

    def add_event_hook(self, fn: Callable[[str, dict], None]) -> None:
        """fn(ev_type, obj) fires once per changed OBJECT (watch events
        and relist diffs alike) -- the payload-carrying feed a keyed
        workqueue consumer needs to stay O(changes)."""
        self._event_hooks.append(fn)

    def _fire(self) -> None:
        for fn in list(self._hooks):
            try:
                fn()
            except Exception:  # noqa: BLE001 - consumer bug must not kill us
                logger.exception("informer change hook failed")

    def _fire_events(self, events: list[tuple[str, dict]]) -> None:
        for fn in list(self._event_hooks):
            for ev_type, obj in events:
                try:
                    fn(ev_type, obj)
                except Exception:  # noqa: BLE001 - consumer bug
                    logger.exception("informer event hook failed")

    def _key(self, obj: dict) -> tuple[str, str]:
        md = obj.get("metadata", {})
        return (md.get("namespace", ""), md.get("name", ""))

    def _on_watch_event(self, ev_type: str, obj: dict) -> None:
        changed = False
        with self._lock:
            key = self._key(obj)
            uid = obj.get("metadata", {}).get("uid", "")
            if ev_type == "DELETED":
                changed = self._cache.pop(key, None) is not None
                if uid:
                    self._by_uid.pop(uid, None)
            else:
                old = self._cache.get(key)
                changed = old != obj
                self._cache[key] = obj
                if uid:
                    self._by_uid[uid] = key
        if changed:
            self._fire_events([(ev_type, obj)])
            self._fire()

    def _on_fake_resource_event(self, group: str, resource: str,
                                namespace: str, ev_type: str,
                                obj: dict) -> None:
        """Resource-scoped FakeKubeClient events apply incrementally:
        exact (group, resource) match, full post-merge objects -- no
        kind guessing, no relist."""
        if self._stop.is_set():
            return
        if group != self.group or resource != self.resource:
            return
        if self.namespace and namespace != self.namespace:
            return
        if not obj.get("metadata", {}).get("name"):
            self.relist()  # unusable payload: conservative fallback
            return
        self._synced.set()
        # The fake store may later mutate this very dict in place (its
        # ADDED payload is the stored object): cache a private copy so
        # change detection compares against what was actually seen.
        self._on_watch_event(ev_type, json.loads(json.dumps(obj)))

    def _on_fake_event(self, ev_type: str, obj: dict) -> None:
        """Legacy global-watcher surface (fakes without resource-scoped
        hooks): filter by kind and relist -- events for other kinds
        can't be told apart reliably, so the conservative path stays."""
        if self._stop.is_set():
            return
        if obj.get("kind") not in (self.kind, None):
            return
        self.relist()

    def _relist_on_gap(self) -> None:
        if self._stop.is_set():
            return
        try:
            self.relist()
        except Exception:  # noqa: BLE001 - the resync loop converges
            logger.exception("relist after watch gap failed")

    def _resync_loop(self) -> None:
        while not self._stop.wait(self.resync_period):
            try:
                self.relist()
            except Exception:  # noqa: BLE001 - transient API failures
                logger.exception("informer relist failed")

    def relist(self) -> None:
        """Full list + cache swap. Concurrent requests coalesce: while
        one relist is in flight, any number of further requests fold
        into a single trailing relist (one per drained burst)."""
        with self._relist_lock:
            if self._relist_active:
                self._relist_pending = True
                return
            self._relist_active = True
        try:
            while True:
                self._relist_once()
                with self._relist_lock:
                    if not self._relist_pending:
                        return
                    self._relist_pending = False
        finally:
            with self._relist_lock:
                self._relist_active = False

    def _relist_once(self) -> None:
        self.relist_total += 1
        if self._on_relist is not None:
            try:
                self._on_relist()
            except Exception:  # noqa: BLE001 - metrics hook
                logger.exception("informer relist hook failed")
        items = self.kube.list(
            self.group, self.version, self.resource,
            namespace=self.namespace,
        )
        with self._lock:
            old = self._cache
            self._cache = {self._key(o): o for o in items}
            self._by_uid = {
                o["metadata"]["uid"]: self._key(o)
                for o in items
                if o.get("metadata", {}).get("uid")
            }
            changed = old != self._cache
            events: list[tuple[str, dict]] = []
            if changed and self._event_hooks:
                for key, obj in self._cache.items():
                    if old.get(key) != obj:
                        ev = "MODIFIED" if key in old else "ADDED"
                        events.append((ev, obj))
                for key, obj in old.items():
                    if key not in self._cache:
                        events.append(("DELETED", obj))
        self._synced.set()
        if changed:
            self._fire_events(events)
            self._fire()

    # -- cache reads ----------------------------------------------------------

    def get_by_uid(self, uid: str) -> dict | None:
        with self._lock:
            key = self._by_uid.get(uid)
            obj = self._cache.get(key) if key else None
            # A delete+recreate under the same (ns, name) during a watch
            # gap leaves the old uid pointing at the new object until the
            # next resync -- never serve an object whose uid differs.
            if obj is not None and obj.get("metadata", {}).get("uid") != uid:
                return None
            return obj

    def get(self, name: str, namespace: str = "") -> dict | None:
        with self._lock:
            return self._cache.get((namespace, name))

    def list(self) -> list[dict]:
        with self._lock:
            return list(self._cache.values())
