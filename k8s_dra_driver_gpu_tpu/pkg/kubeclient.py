"""Minimal Kubernetes REST client + in-memory fake.

Reference: pkg/flags/kubeclient.go builds ClientSets{Core, Nvidia,
Resource} from kubeconfig/in-cluster config. This runtime has no official
client dependency, so this is a small typed wrapper over the REST API:
CRUD on arbitrary group/version/resource paths, JSON-merge patch, and a
bounded watch. The FakeKubeClient implements the same surface in memory
for unit tests (the analog of the reference's generated fake clientset,
pkg/nvidia.com/clientset/versioned/fake/).
"""

from __future__ import annotations

import json
import logging
import os
import re
import ssl
import threading
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

from . import faults

logger = logging.getLogger(__name__)

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"{status}: {message}")
        self.status = status


class NotFoundError(KubeError):
    def __init__(self, message: str = "not found"):
        super().__init__(404, message)


class ConflictError(KubeError):
    def __init__(self, message: str = "conflict"):
        super().__init__(409, message)


def _resource_path(
    group: str, version: str, resource: str, namespace: str | None, name: str | None
) -> str:
    base = f"/api/{version}" if not group else f"/apis/{group}/{version}"
    if namespace:
        base += f"/namespaces/{namespace}"
    base += f"/{resource}"
    if name:
        base += f"/{name}"
    return base


class KubeClient:
    """REST client over the API server (in-cluster or kubeconfig host)."""

    def __init__(
        self,
        host: str | None = None,
        token: str | None = None,
        ca_cert: str | None = None,
        ca_data: str | None = None,
        client_cert: str | None = None,
        client_key: str | None = None,
        insecure: bool = False,
    ):
        if host is None:
            # KUBE_API: explicit full URL (binaries' --kube-api flag
            # mirror; lets processes launched as "pods" by the fake
            # node reach the fake apiserver over plain HTTP).
            host = os.environ.get("KUBE_API")
        if host is None:
            h = os.environ.get("KUBERNETES_SERVICE_HOST")
            p = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not h:
                raise KubeError(0, "no API server host configured")
            host = f"https://{h}:{p}"
        self._host = host.rstrip("/")
        if token is None:
            token_path = os.path.join(SERVICE_ACCOUNT_DIR, "token")
            if os.path.exists(token_path):
                with open(token_path) as f:
                    token = f.read().strip()
        self._token = token
        ctx: ssl.SSLContext | None = None
        if self._host.startswith("https"):
            ctx = ssl.create_default_context()
            if ca_data:
                ctx.load_verify_locations(cadata=ca_data)
            else:
                ca = ca_cert or os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt")
                if os.path.exists(ca):
                    ctx.load_verify_locations(ca)
            if client_cert:
                ctx.load_cert_chain(client_cert, keyfile=client_key)
            if insecure:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
        self._ssl = ctx

    @classmethod
    def from_kubeconfig(
        cls, path: str | None = None, context: str | None = None
    ) -> "KubeClient":
        """Build a client from a kubeconfig (token or client-cert auth;
        the e2e tier's entry point -- the rest of the stack runs
        in-cluster with service-account credentials)."""
        import base64  # noqa: PLC0415
        import tempfile  # noqa: PLC0415

        import yaml  # noqa: PLC0415

        path = path or os.environ.get(
            "KUBECONFIG", os.path.expanduser("~/.kube/config"))
        with open(path, encoding="utf-8") as f:
            doc = yaml.safe_load(f)
        base_dir = os.path.dirname(os.path.abspath(path))

        def resolve(p: str | None) -> str | None:
            # kubectl resolves relative cert paths against the
            # kubeconfig's own directory, not the process CWD.
            if p and not os.path.isabs(p):
                return os.path.join(base_dir, p)
            return p

        ctx_name = context or doc.get("current-context", "")

        def pick(section: str, name: str, inner: str) -> dict:
            match = next((e[inner] for e in doc.get(section, [])
                          if e.get("name") == name), None)
            if match is None:
                raise KubeError(
                    0, f"kubeconfig {path}: no {inner} named {name!r} "
                       f"in {section} (current-context unset?)")
            return match

        ctx = pick("contexts", ctx_name, "context")
        cluster = pick("clusters", ctx["cluster"], "cluster")
        user = pick("users", ctx["user"], "user")

        def materialize(data_key: str, file_key: str) -> str | None:
            if user.get(data_key):
                import atexit  # noqa: PLC0415

                fd, tmp_path = tempfile.mkstemp(suffix=".pem")
                os.fchmod(fd, 0o600)  # decoded private-key material
                with os.fdopen(fd, "wb") as tf:
                    tf.write(base64.b64decode(user[data_key]))
                atexit.register(
                    lambda p=tmp_path: os.path.exists(p) and os.unlink(p))
                return tmp_path
            return resolve(user.get(file_key))

        ca_data = None
        if cluster.get("certificate-authority-data"):
            ca_data = base64.b64decode(
                cluster["certificate-authority-data"]).decode()
        return cls(
            host=cluster["server"],
            token=user.get("token", ""),
            ca_cert=resolve(cluster.get("certificate-authority")),
            ca_data=ca_data,
            client_cert=materialize("client-certificate-data",
                                    "client-certificate"),
            client_key=materialize("client-key-data", "client-key"),
            insecure=bool(cluster.get("insecure-skip-tls-verify")),
        )

    def read_raw(self, path: str, timeout: float = 30.0) -> str:
        """GET returning the raw body (pod logs are not JSON). Shares
        the JSON surface's auth + error mapping."""
        return self._request("GET", path, timeout=timeout, raw=True)

    def _request(
        self, method: str, path: str, body: dict | None = None,
        content_type: str = "application/json", timeout: float = 30.0,
        raw: bool = False,
    ):
        url = self._host + path
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "*/*" if raw else "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        try:
            with urllib.request.urlopen(
                req, timeout=timeout, context=self._ssl
            ) as resp:
                payload = resp.read()
                if raw:
                    return payload.decode(errors="replace")
                return json.loads(payload) if payload else {}
        except urllib.error.HTTPError as e:
            msg = e.read().decode(errors="replace")
            if e.code == 404:
                raise NotFoundError(msg) from e
            if e.code == 409:
                raise ConflictError(msg) from e
            raise KubeError(e.code, msg) from e

    # -- typed surface --------------------------------------------------------
    # Every verb accepts an explicit per-attempt ``timeout`` (seconds);
    # the RetryingKubeClient wrapper (pkg/retry.py) supplies one on each
    # attempt so no call can park a thread for the urllib default.

    def get(self, group, version, resource, name, namespace=None,
            timeout: float = 30.0) -> dict:
        return self._request(
            "GET", _resource_path(group, version, resource, namespace, name),
            timeout=timeout,
        )

    def list(self, group, version, resource, namespace=None,
             label_selector: str | None = None,
             field_selector: str | None = None,
             timeout: float = 30.0) -> list[dict]:
        path = _resource_path(group, version, resource, namespace, None)
        query = []
        if label_selector:
            query.append(
                f"labelSelector={urllib.request.quote(label_selector)}")
        if field_selector:
            query.append(
                f"fieldSelector={urllib.request.quote(field_selector)}")
        if query:
            path += "?" + "&".join(query)
        return self._request("GET", path, timeout=timeout).get("items", [])

    def create(self, group, version, resource, obj, namespace=None,
               timeout: float = 30.0) -> dict:
        return self._request(
            "POST", _resource_path(group, version, resource, namespace, None),
            body=obj, timeout=timeout,
        )

    def update(self, group, version, resource, name, obj, namespace=None,
               timeout: float = 30.0) -> dict:
        return self._request(
            "PUT", _resource_path(group, version, resource, namespace, name),
            body=obj, timeout=timeout,
        )

    def patch(self, group, version, resource, name, patch, namespace=None,
              timeout: float = 30.0) -> dict:
        return self._request(
            "PATCH", _resource_path(group, version, resource, namespace, name),
            body=patch, content_type="application/merge-patch+json",
            timeout=timeout,
        )

    def delete(self, group, version, resource, name, namespace=None,
               timeout: float = 30.0) -> None:
        try:
            self._request(
                "DELETE",
                _resource_path(group, version, resource, namespace, name),
                timeout=timeout,
            )
        except NotFoundError:
            pass

    def server_version(self, timeout: float = 30.0) -> dict:
        return self._request("GET", "/version", timeout=timeout)

    # -- watch ----------------------------------------------------------------

    def watch(
        self,
        group: str,
        version: str,
        resource: str,
        on_event: Callable[[str, dict], None],
        namespace: str | None = None,
        stop: threading.Event | None = None,
        reconnect_delay: float = 2.0,
        on_gap: Callable[[], None] | None = None,
    ) -> threading.Thread:
        """Streamed watch (chunked JSON lines, `?watch=true`), with
        resourceVersion bookmarking and automatic reconnect. Events are
        delivered as on_event(type, object) -- the same surface as
        FakeKubeClient watchers. Returns the (daemon) watch thread.

        After a 410 Gone (resourceVersion aged out of the watch cache)
        the stream resumes from "now" without replaying the gap --
        ``on_gap`` fires at that moment so the consumer can RELIST
        immediately (informer-style) instead of waiting for its periodic
        resync; consumers without on_gap MUST still pair the watch with
        a resync to converge on anything missed."""
        stop = stop or threading.Event()

        def gap():
            if on_gap is None:
                return
            try:
                on_gap()
            except Exception:  # noqa: BLE001
                logger.exception("watch gap callback failed for %s",
                                 resource)

        def run():
            resource_version = ""
            while not stop.is_set():
                path = _resource_path(group, version, resource, namespace,
                                      None)
                query = "?watch=true&allowWatchBookmarks=true"
                if resource_version:
                    query += f"&resourceVersion={resource_version}"
                url = self._host + path + query
                req = urllib.request.Request(url)
                req.add_header("Accept", "application/json")
                if self._token:
                    req.add_header("Authorization", f"Bearer {self._token}")
                try:
                    # Fault seam: error mode simulates a broken watch
                    # stream (apiserver blip); the reconnect + gap
                    # handling below is exactly what it exercises.
                    faults.fault_point("kube.watch",
                                       error=lambda m: OSError(m))
                    with urllib.request.urlopen(
                        req, timeout=300, context=self._ssl
                    ) as resp:
                        for raw in resp:
                            if stop.is_set():
                                return
                            line = raw.strip()
                            if not line:
                                continue
                            try:
                                ev = json.loads(line)
                            except json.JSONDecodeError:
                                continue
                            obj = ev.get("object", {})
                            rv = obj.get("metadata", {}).get(
                                "resourceVersion")
                            if rv:
                                resource_version = rv
                            ev_type = ev.get("type", "")
                            if ev_type == "BOOKMARK":
                                continue
                            if ev_type == "ERROR":
                                resource_version = ""  # relist from now
                                gap()
                                break
                            if not ev_type or not obj.get("metadata"):
                                continue  # not a usable watch event
                            try:
                                on_event(ev_type, obj)
                            except Exception:  # noqa: BLE001
                                # A callback bug must not kill the watch.
                                logger.exception(
                                    "watch callback failed for %s %s",
                                    ev_type, resource,
                                )
                except urllib.error.HTTPError as e:
                    if e.code == 410:
                        # Expired resourceVersion at watch establishment
                        # (long disconnect): drop the bookmark and
                        # re-watch from "now" instead of redialing with
                        # the stale version forever. Events from the gap
                        # are NOT replayed -- on_gap lets the consumer
                        # relist right away.
                        resource_version = ""
                        gap()
                except (urllib.error.URLError, OSError, TimeoutError):
                    pass
                stop.wait(reconnect_delay)

        thread = threading.Thread(
            target=run, name=f"watch-{resource}", daemon=True
        )
        thread.start()
        return thread


@dataclass
class _WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    obj: dict


class FakeKubeClient:
    """In-memory KubeClient with the same surface + watch hooks."""

    def __init__(self):
        # (group, resource, namespace or "", name) -> obj
        self._store: dict[tuple, dict] = {}
        self._lock = threading.Lock()
        self._watchers: list[Callable[[str, dict], None]] = []
        # fn(group, resource, namespace, event_type, obj) -- scoped
        # events for consumers that multiplex resources (fakeapiserver).
        self._resource_watchers: list[Callable] = []
        self._uid = 0
        self.version = {"major": "1", "minor": "34"}

    # -- helpers --------------------------------------------------------------

    def _key(self, group, resource, namespace, name):
        return (group, resource, namespace or "", name)

    def _notify(self, event_type: str, obj: dict,
                group: str = "", resource: str = "",
                namespace: str = "") -> None:
        for w in list(self._watchers):
            w(event_type, obj)
        for rw in list(self._resource_watchers):
            rw(group, resource, namespace, event_type, obj)

    def add_watcher(self, fn: Callable[[str, dict], None]) -> None:
        self._watchers.append(fn)

    def add_resource_watcher(self, fn: Callable) -> None:
        self._resource_watchers.append(fn)

    def remove_resource_watcher(self, fn: Callable) -> None:
        try:
            self._resource_watchers.remove(fn)
        except ValueError:
            pass

    def objects(self, group=None, resource=None) -> list[dict]:
        with self._lock:
            return [
                v for (g, r, _, _), v in self._store.items()
                if (group is None or g == group)
                and (resource is None or r == resource)
            ]

    # -- surface --------------------------------------------------------------
    # ``timeout`` mirrors the real client's per-attempt timeout and is
    # ignored (in-memory store); keeping the signatures identical lets
    # the RetryingKubeClient wrapper treat both clients uniformly.

    def get(self, group, version, resource, name, namespace=None,
            timeout: float = 30.0) -> dict:
        with self._lock:
            obj = self._store.get(self._key(group, resource, namespace, name))
            if obj is None:
                raise NotFoundError(f"{resource}/{name}")
            return json.loads(json.dumps(obj))

    def list(self, group, version, resource, namespace=None,
             label_selector: str | None = None,
             field_selector: str | None = None,
             timeout: float = 30.0) -> list[dict]:
        sel = {}
        if label_selector:
            for part in label_selector.split(","):
                k, _, v = part.partition("=")
                sel[k] = v
        fields = {}
        if field_selector:
            for part in field_selector.split(","):
                k, _, v = part.partition("=")
                fields[k] = v

        def field_val(obj, dotted):
            cur = obj
            for seg in dotted.split("."):
                if not isinstance(cur, dict):
                    return None
                cur = cur.get(seg)
            return cur

        with self._lock:
            out = []
            for (g, r, ns, _), obj in self._store.items():
                if g != group or r != resource:
                    continue
                if namespace and ns != namespace:
                    continue
                labels = obj.get("metadata", {}).get("labels", {})
                if not all(labels.get(k) == v for k, v in sel.items()):
                    continue
                if all(field_val(obj, k) == v for k, v in fields.items()):
                    out.append(json.loads(json.dumps(obj)))
            return out

    def create(self, group, version, resource, obj, namespace=None,
               timeout: float = 30.0) -> dict:
        name = obj.get("metadata", {}).get("name", "")
        key = self._key(group, resource, namespace, name)
        with self._lock:
            if key in self._store:
                raise ConflictError(f"{resource}/{name} exists")
            obj = json.loads(json.dumps(obj))
            meta = obj.setdefault("metadata", {})
            if namespace:
                meta.setdefault("namespace", namespace)
            if not meta.get("uid"):
                self._uid += 1
                meta["uid"] = f"uid-{self._uid}"
            meta["resourceVersion"] = "1"
            self._store[key] = obj
        self._notify("ADDED", obj, group, resource, namespace or "")
        return json.loads(json.dumps(obj))

    def update(self, group, version, resource, name, obj, namespace=None,
               timeout: float = 30.0) -> dict:
        key = self._key(group, resource, namespace, name)
        with self._lock:
            if key not in self._store:
                raise NotFoundError(f"{resource}/{name}")
            old = self._store[key]
            obj = json.loads(json.dumps(obj))
            meta = obj.setdefault("metadata", {})
            meta.setdefault("uid", old.get("metadata", {}).get("uid"))
            old_rv = old.get("metadata", {}).get("resourceVersion", "1")
            # Apiserver optimistic concurrency: an update carrying a
            # resourceVersion must match the stored one or 409 -- this
            # is what makes fetch-modify-update retry loops (registrar,
            # leader election) actually exercise their conflict paths.
            # An update WITHOUT a resourceVersion is accepted (k8s
            # last-write semantics for rv-less updates).
            rv_in = meta.get("resourceVersion")
            if rv_in and rv_in != old_rv:
                raise ConflictError(
                    f"{resource}/{name}: resourceVersion {rv_in} is "
                    f"stale (current {old_rv})")
            meta["resourceVersion"] = str(int(old_rv) + 1)
            self._store[key] = obj
        self._notify("MODIFIED", obj, group, resource, namespace or "")
        return json.loads(json.dumps(obj))

    def patch(self, group, version, resource, name, patch, namespace=None,
              timeout: float = 30.0) -> dict:
        def merge(dst, src):
            for k, v in src.items():
                if v is None:
                    dst.pop(k, None)
                elif isinstance(v, dict) and isinstance(dst.get(k), dict):
                    merge(dst[k], v)
                else:
                    dst[k] = v
        key = self._key(group, resource, namespace, name)
        with self._lock:
            if key not in self._store:
                raise NotFoundError(f"{resource}/{name}")
            obj = self._store[key]
            stored_rv = obj.get("metadata", {}).get("resourceVersion", "1")
            patch = json.loads(json.dumps(patch))
            # The apiserver treats a resourceVersion INSIDE a
            # merge-patch body as an optimistic-concurrency
            # precondition: mismatch is a 409, match applies. Rv-less
            # patches keep last-write-wins semantics. Either way the
            # body rv is consumed here -- it must never rewind the
            # stored counter update() enforces against.
            rv_in = patch.get("metadata", {}).pop("resourceVersion", None)
            if rv_in is not None and str(rv_in) != stored_rv:
                raise ConflictError(
                    f"{resource}/{name}: resourceVersion {rv_in} is "
                    f"stale (current {stored_rv})")
            merge(obj, patch)
            obj.setdefault("metadata", {})["resourceVersion"] = stored_rv
            rv = int(obj.get("metadata", {}).get("resourceVersion", "1"))
            obj["metadata"]["resourceVersion"] = str(rv + 1)
            out = json.loads(json.dumps(obj))
        self._notify("MODIFIED", out, group, resource, namespace or "")
        return out

    def delete(self, group, version, resource, name, namespace=None,
               timeout: float = 30.0) -> None:
        key = self._key(group, resource, namespace, name)
        with self._lock:
            obj = self._store.pop(key, None)
            cascade = []
            if obj is not None and resource == "namespaces" and not namespace:
                # Namespace deletion GCs every namespaced object in it,
                # like the real namespace controller (so e2e teardown
                # frees allocated devices and claims).
                for k in [k for k in self._store if k[2] == name]:
                    cascade.append((k, self._store.pop(k)))
        if obj is not None:
            self._notify("DELETED", obj, group, resource, namespace or "")
        for (g, r, ns, _), victim in cascade:
            self._notify("DELETED", victim, g, r, ns)

    def server_version(self, timeout: float = 30.0) -> dict:
        return self.version

    def read_raw(self, path: str, timeout: float = 30.0) -> str:
        """Raw-body read for the fake: pod-log style paths resolve to a
        `fake/log` annotation on the object; anything else is 404."""
        m = re.match(
            r"^/api/v1/namespaces/([^/]+)/pods/([^/]+)/log$", path)
        if m:
            obj = self.get("", "v1", "pods", m.group(2),
                           namespace=m.group(1))
            return obj.get("metadata", {}).get(
                "annotations", {}).get("fake/log", "")
        raise NotFoundError(path)
