"""Shared ResourceSlice publication (used by both DRA drivers).

Reference: the kubeletplugin helper's PublishResources
(gpu driver.go:455, CD plugin equivalent).
"""

from __future__ import annotations

from .kubeclient import NotFoundError

RESOURCE_GROUP = "resource.k8s.io"
RESOURCE_VERSION = "v1"


def publish_resource_slices(kube, slices: list[dict]) -> None:
    """Create-or-update each slice, bumping the pool generation on
    update so schedulers see a fresh pool snapshot."""
    for obj in slices:
        name = obj["metadata"]["name"]
        try:
            existing = kube.get(
                RESOURCE_GROUP, RESOURCE_VERSION, "resourceslices", name
            )
            obj["spec"]["pool"]["generation"] = (
                existing["spec"]["pool"]["generation"] + 1
            )
            kube.update(
                RESOURCE_GROUP, RESOURCE_VERSION, "resourceslices", name, obj
            )
        except NotFoundError:
            kube.create(
                RESOURCE_GROUP, RESOURCE_VERSION, "resourceslices", obj
            )
