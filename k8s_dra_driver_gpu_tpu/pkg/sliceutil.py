"""Shared ResourceSlice publication (used by both DRA drivers).

Reference: the kubeletplugin helper's PublishResources
(gpu driver.go:455, CD plugin equivalent). Like the upstream helper, one
publish pass stamps a single shared pool generation on every slice of
the pool and deletes slices of this driver/node that are no longer in
the desired set (e.g. after a combined->split mode transition), so no
stale slice can shadow the pool at a higher generation.
"""

from __future__ import annotations

from .kubeclient import NotFoundError

RESOURCE_GROUP = "resource.k8s.io"
RESOURCE_VERSION = "v1"


def _existing_pool_slices(kube, driver: str, node_name: str) -> list[dict]:
    # ResourceSlice supports spec.driver/spec.nodeName field selectors;
    # scope the list server-side so an N-node rollout doesn't make every
    # node fetch the whole cluster's slices. Client-side filter retained
    # as a belt for clients that ignore the selector.
    items = kube.list(
        RESOURCE_GROUP, RESOURCE_VERSION, "resourceslices",
        field_selector=f"spec.driver={driver},spec.nodeName={node_name}",
    )
    return [
        s for s in items
        if s.get("spec", {}).get("driver") == driver
        and s.get("spec", {}).get("nodeName") == node_name
    ]


def publish_resource_slices(kube, slices: list[dict]) -> None:
    """Publish the desired slice set for one (driver, node) pool.

    All slices must belong to the same driver/node. The whole set gets
    one pool generation (max existing + 1); stale slices of that pool
    are deleted. An empty set is a no-op (the pool identity would be
    unknown): a driver with zero devices still publishes one slice with
    an empty device list rather than an empty set, which is what both
    in-tree drivers do.
    """
    if not slices:
        return
    driver = slices[0]["spec"]["driver"]
    node_name = slices[0]["spec"]["nodeName"]
    existing = _existing_pool_slices(kube, driver, node_name)
    existing_by_name = {s["metadata"]["name"]: s for s in existing}
    generation = 1 + max(
        (s["spec"].get("pool", {}).get("generation", 0) for s in existing),
        default=0,
    )
    desired_names = set()
    for obj in slices:
        name = obj["metadata"]["name"]
        desired_names.add(name)
        obj["spec"]["pool"]["generation"] = generation
        if name in existing_by_name:
            try:
                kube.update(
                    RESOURCE_GROUP, RESOURCE_VERSION, "resourceslices", name, obj
                )
            except NotFoundError:
                kube.create(
                    RESOURCE_GROUP, RESOURCE_VERSION, "resourceslices", obj
                )
        else:
            kube.create(
                RESOURCE_GROUP, RESOURCE_VERSION, "resourceslices", obj
            )
    for name in existing_by_name:
        if name not in desired_names:
            kube.delete(RESOURCE_GROUP, RESOURCE_VERSION, "resourceslices", name)
