"""Shared ResourceSlice publication (used by both DRA drivers).

Reference: the kubeletplugin helper's PublishResources
(gpu driver.go:455, CD plugin equivalent). Like the upstream helper, one
publish pass stamps a single shared pool generation on every slice of
the pool and deletes slices of this driver/node that are no longer in
the desired set (e.g. after a combined->split mode transition), so no
stale slice can shadow the pool at a higher generation.

Write-amplification discipline: the desired spec is diffed against the
live spec by CANONICAL CONTENT HASH (``slice_content_hash``: the spec
with the pool generation masked out). A publish whose desired set
matches the live set performs ZERO kube writes -- the health monitor's
periodic republish of an unchanged taint set no longer rewrites the
pool every poll -- and the pool generation is bumped only when the
DEVICE INVENTORY actually changed (a device appearing, disappearing, or
moving between slices). A content-only change on an unchanged inventory
(taint flips, attribute updates) rewrites just the changed slices at
the CURRENT generation: the real kube-scheduler DRA plugin (KEP-4381)
treats a generation bump as inventory churn and re-evaluates the whole
pool, so taint noise must not masquerade as churn.
"""

from __future__ import annotations

import hashlib
import json

from .kubeclient import NotFoundError

RESOURCE_GROUP = "resource.k8s.io"
RESOURCE_VERSION = "v1"


def slice_content_hash(obj: dict) -> str:
    """Canonical content hash of a ResourceSlice's spec with the pool
    generation masked out: two slices that differ only by generation
    (or metadata bookkeeping) hash identically."""
    spec = dict(obj.get("spec", {}))
    pool = dict(spec.get("pool") or {})
    pool.pop("generation", None)
    spec["pool"] = pool
    blob = json.dumps(spec, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def _device_names(obj: dict) -> list[str]:
    return [d.get("name", "") for d in obj.get("spec", {}).get(
        "devices", [])]


def _existing_pool_slices(kube, driver: str, node_name: str) -> list[dict]:
    # ResourceSlice supports spec.driver/spec.nodeName field selectors;
    # scope the list server-side so an N-node rollout doesn't make every
    # node fetch the whole cluster's slices. Client-side filter retained
    # as a belt for clients that ignore the selector.
    items = kube.list(
        RESOURCE_GROUP, RESOURCE_VERSION, "resourceslices",
        field_selector=f"spec.driver={driver},spec.nodeName={node_name}",
    )
    return [
        s for s in items
        if s.get("spec", {}).get("driver") == driver
        and s.get("spec", {}).get("nodeName") == node_name
    ]


def publish_resource_slices(kube, slices: list[dict], diff: bool = True,
                            on_skip=None) -> dict:
    """Publish the desired slice set for one (driver, node) pool.

    All slices must belong to the same driver/node. An empty set is a
    no-op (the pool identity would be unknown): a driver with zero
    devices still publishes one slice with an empty device list rather
    than an empty set, which is what both in-tree drivers do.

    With ``diff`` (the default) the desired set is compared against the
    live set by content hash:

    - identical -> zero writes (``skipped`` counts the no-op PUTs
      avoided; ``on_skip(n)`` fires for metrics).
    - same slice names AND same per-slice device-name inventory at one
      shared generation -> only the changed slices are rewritten, at
      the CURRENT generation (no bump: taint/attribute updates are not
      inventory churn).
    - anything else (slices added/removed, devices added/removed/moved,
      or a previously inconsistent pool) -> the whole set is written at
      max(existing)+1 and stale slices are deleted, exactly the legacy
      behavior.

    ``diff=False`` forces that legacy write-always path (the polled
    baseline mode in bench.py --sched-churn).

    Returns ``{"writes", "deletes", "skipped", "generation",
    "changed"}``.
    """
    stats = {"writes": 0, "deletes": 0, "skipped": 0,
             "generation": None, "changed": False}
    if not slices:
        return stats
    driver = slices[0]["spec"]["driver"]
    node_name = slices[0]["spec"]["nodeName"]
    existing = _existing_pool_slices(kube, driver, node_name)
    existing_by_name = {s["metadata"]["name"]: s for s in existing}
    desired_names = {obj["metadata"]["name"] for obj in slices}
    existing_gens = {
        s["spec"].get("pool", {}).get("generation", 0) for s in existing
    }

    if diff and desired_names == set(existing_by_name) and \
            len(existing_gens) == 1:
        unchanged = {
            name for name in desired_names
            if slice_content_hash(existing_by_name[name])
            == slice_content_hash(next(
                o for o in slices if o["metadata"]["name"] == name))
        }
        generation = next(iter(existing_gens))
        if len(unchanged) == len(desired_names):
            # Fully converged: zero kube writes, generation untouched.
            stats["skipped"] = len(slices)
            stats["generation"] = generation
            if on_skip is not None:
                on_skip(len(slices))
            return stats
        same_inventory = all(
            _device_names(obj)
            == _device_names(existing_by_name[obj["metadata"]["name"]])
            for obj in slices
        )
        if same_inventory:
            # Content-only change (taints, attributes): rewrite just
            # the changed slices at the CURRENT generation -- device
            # inventory did not change, so consumers must not see a
            # pool-generation bump.
            for obj in slices:
                name = obj["metadata"]["name"]
                obj["spec"]["pool"]["generation"] = generation
                if name in unchanged:
                    stats["skipped"] += 1
                    continue
                try:
                    kube.update(RESOURCE_GROUP, RESOURCE_VERSION,
                                "resourceslices", name, obj)
                except NotFoundError:
                    kube.create(RESOURCE_GROUP, RESOURCE_VERSION,
                                "resourceslices", obj)
                stats["writes"] += 1
            stats["generation"] = generation
            stats["changed"] = True
            if on_skip is not None and stats["skipped"]:
                on_skip(stats["skipped"])
            return stats

    # Inventory change (or legacy/no-diff path): one new shared pool
    # generation over the whole desired set; stale slices deleted so
    # they can never shadow the pool at a higher generation.
    generation = 1 + max(existing_gens, default=0)
    for obj in slices:
        name = obj["metadata"]["name"]
        obj["spec"]["pool"]["generation"] = generation
        if name in existing_by_name:
            try:
                kube.update(
                    RESOURCE_GROUP, RESOURCE_VERSION, "resourceslices",
                    name, obj
                )
            except NotFoundError:
                kube.create(
                    RESOURCE_GROUP, RESOURCE_VERSION, "resourceslices", obj
                )
        else:
            kube.create(
                RESOURCE_GROUP, RESOURCE_VERSION, "resourceslices", obj
            )
        stats["writes"] += 1
    for name in existing_by_name:
        if name not in desired_names:
            kube.delete(RESOURCE_GROUP, RESOURCE_VERSION,
                        "resourceslices", name)
            stats["deletes"] += 1
    stats["generation"] = generation
    stats["changed"] = True
    return stats
