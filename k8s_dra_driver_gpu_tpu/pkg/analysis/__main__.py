"""CLI runner: ``python -m k8s_dra_driver_gpu_tpu.pkg.analysis``.

Exit status is 0 when every finding is baselined (or none exist), 1
otherwise -- the ``make lint-analysis`` / CI contract.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .lint import RULES, Baseline, metrics_exposition, run_lint

DEFAULT_BASELINE = "analysis-baseline.json"


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="tpu-dra-analysis",
        description="Concurrency invariant linter (lock hierarchy, "
                    "checkpoint state machine, informer-cache "
                    "discipline). Rule IDs TPUDRA001..; see "
                    "docs/analysis.md.",
    )
    p.add_argument("paths", nargs="*", default=["k8s_dra_driver_gpu_tpu"],
                   help="files/directories to lint "
                        "(default: k8s_dra_driver_gpu_tpu)")
    p.add_argument("--root", default=".",
                   help="path root for fingerprints (default: cwd)")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help=f"baseline suppression file "
                        f"(default: {DEFAULT_BASELINE})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline file entirely")
    p.add_argument("--update-baseline", action="store_true",
                   help="write every current finding into the baseline "
                        "and exit 0")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout (for "
                        "dashboard ingestion)")
    p.add_argument("--metrics-out", default=None, metavar="FILE",
                   help="write a Prometheus text summary "
                        "(tpu_dra_lint_findings_total by rule) to FILE")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    paths = args.paths or ["k8s_dra_driver_gpu_tpu"]
    baseline = None if args.no_baseline else Baseline.load(args.baseline)
    report = run_lint(paths, baseline=baseline,
                      root=os.path.abspath(args.root))

    if args.update_baseline:
        # REBUILD from the current findings (keeping reasons for the
        # survivors) rather than merging: a stale fingerprint left
        # behind would silently re-suppress the same-shaped defect if
        # it is ever reintroduced.
        old = baseline.suppressions if baseline else {}
        bl = Baseline(path=args.baseline)
        for f in report.findings:
            bl.suppressions[f.fingerprint] = old.get(
                f.fingerprint, "baselined finding")
        pruned = len(set(old) - set(bl.suppressions))
        bl.save(args.baseline)
        print(f"baseline updated: {len(bl.suppressions)} suppression(s)"
              f" ({pruned} stale pruned) -> {args.baseline}")
        return 0

    if args.metrics_out:
        with open(args.metrics_out, "w", encoding="utf-8") as f:
            f.write(metrics_exposition(report))

    if args.as_json:
        json.dump(report.to_dict(), sys.stdout, indent=1)
        print()
    else:
        for f in report.findings:
            print(f)
        counts = report.counts()
        total = sum(counts.values())
        print(f"{report.files_scanned} file(s) scanned; {total} "
              f"non-baselined finding(s), {len(report.baselined)} "
              "baselined")
        if total:
            for rule, n in sorted(counts.items()):
                if n:
                    print(f"  {rule}: {n}  ({RULES[rule]})")
    return 1 if report.active else 0


if __name__ == "__main__":
    sys.exit(main())
