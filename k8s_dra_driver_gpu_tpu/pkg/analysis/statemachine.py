"""The checkpoint claim state machine, as a checked artifact.

Legal lifecycles (docs/architecture.md "Crash safety model"):

- **Two-phase** (chip kubelet plugin, ``kubeletplugin/device_state.py``):
  absent -> PrepareStarted (the durable reservation) -> PrepareCompleted,
  torn down from either state back to absent (failure rollback /
  unprepare). A claim may NEVER appear as PrepareCompleted without its
  PrepareStarted reservation having been durable first -- that ordering
  is what crash recovery replays against.
- **Single-phase** (compute-domain kubelet plugin,
  ``computedomain/plugin/device_state.py``): channel/daemon prepares
  mutate no device state, so they write PrepareCompleted in one step;
  PrepareStarted must never appear in a CD checkpoint.

``TransitionPolicy`` is the declarative model; CheckpointManager runs
``validate_states`` on every group-committed mutation (the runtime
validator), and the AST pass (lint rule TPUDRA007) verifies every
CheckpointManager construction site in the package declares which
policy it lives under -- so a new mutation site cannot silently opt
out of the model.

This module is dependency-free on purpose: kubeletplugin/checkpoint.py
imports it, so it must not import anything from kubeletplugin back.
"""

from __future__ import annotations

# Canonical state names. kubeletplugin/checkpoint.py's ClaimState enum
# must agree with these (tests/test_analysis_statemachine.py pins it).
ABSENT = None
PREPARE_STARTED = "PrepareStarted"
PREPARE_COMPLETED = "PrepareCompleted"


class CheckpointTransitionError(RuntimeError):
    """A checkpoint mutation attempted an illegal claim-state
    transition. Raised inside the group-commit flush, so the batch
    fails and the read cache is poisoned -- the illegal state never
    becomes durable and never surfaces from the cache."""


class TransitionPolicy:
    """A declarative set of legal (old_state, new_state) transitions.

    ``None`` stands for "claim absent from the checkpoint". Identity
    transitions (old == new) are always legal: idempotent re-writes of
    an unchanged state (e.g. a retried reservation after rollback)
    carry no lifecycle meaning.
    """

    def __init__(self, name: str,
                 allowed: frozenset[tuple[str | None, str | None]]):
        self.name = name
        self.allowed = frozenset(allowed)

    def __repr__(self) -> str:  # diagnostics in transition errors
        return f"TransitionPolicy({self.name!r})"

    def is_legal(self, old: str | None, new: str | None) -> bool:
        return old == new or (old, new) in self.allowed

    def validate(self, uid: str, old: str | None, new: str | None) -> None:
        if not self.is_legal(old, new):
            raise CheckpointTransitionError(
                f"claim {uid}: illegal checkpoint transition "
                f"{old or 'absent'} -> {new or 'absent'} under the "
                f"{self.name} policy (legal: "
                f"{sorted((o or 'absent', n or 'absent') for o, n in self.allowed)})"
            )

    def validate_states(
        self,
        old_states: dict[str, str],
        new_states: dict[str, str],
        scope=None,
    ) -> None:
        """Validate every per-claim state change between two checkpoint
        snapshots. ``scope`` (an iterable of uids, or None for all)
        narrows the check to the claims one commit declared dirty --
        but a commit that mutated OUTSIDE its declared scope is itself
        a bug, so out-of-scope changes fail too."""
        uids = set(old_states) | set(new_states)
        scoped = set(scope) if scope is not None else None
        for uid in uids:
            old = old_states.get(uid)
            new = new_states.get(uid)
            if old == new:
                continue
            if scoped is not None and uid not in scoped:
                raise CheckpointTransitionError(
                    f"claim {uid}: checkpoint mutation changed state "
                    f"{old or 'absent'} -> {new or 'absent'} outside its "
                    f"declared dirty set {sorted(scoped)}"
                )
            self.validate(uid, old, new)


TWO_PHASE_POLICY = TransitionPolicy(
    "two-phase",
    frozenset({
        (ABSENT, PREPARE_STARTED),            # durable reservation
        (PREPARE_STARTED, PREPARE_COMPLETED),  # middle finished
        (PREPARE_STARTED, ABSENT),             # failure/stale rollback
        (PREPARE_COMPLETED, ABSENT),           # unprepare
    }),
)

SINGLE_PHASE_POLICY = TransitionPolicy(
    "single-phase",
    frozenset({
        (ABSENT, PREPARE_COMPLETED),  # one-step prepare (no device state)
        (PREPARE_COMPLETED, ABSENT),  # unprepare
    }),
)

# -- eviction (permanent-failure recovery, pkg/recovery.py) -------------------
#
# The claim-eviction controller persists one record per in-flight
# eviction through the same group-committed CheckpointManager the node
# plugins use, so a controller crash mid-eviction resumes exactly where
# the durable record says it stopped. States:
#
#   absent -> EvictionPlanned      (failure declared, move planned)
#   EvictionPlanned -> EvictionDraining    (consumer pods evicted,
#                                           reservations dropped)
#   EvictionDraining -> EvictionDeallocated (allocation cleared; the
#                                           incremental scheduler owns
#                                           re-placement from here)
#   <any> -> absent                (re-placed, claim gone, or cleanly
#                                   failed at the recovery deadline)
#
# Skipping a stage (absent -> Draining, Planned -> Deallocated) would
# mean a drain or deallocation ran without its durable intent record --
# exactly the class of bug the runtime validator exists to catch.

EVICTION_PLANNED = "EvictionPlanned"
EVICTION_DRAINING = "EvictionDraining"
EVICTION_DEALLOCATED = "EvictionDeallocated"

EVICTION_POLICY = TransitionPolicy(
    "eviction",
    frozenset({
        (ABSENT, EVICTION_PLANNED),               # failure declared
        (EVICTION_PLANNED, EVICTION_DRAINING),    # pods evicted
        (EVICTION_DRAINING, EVICTION_DEALLOCATED),  # allocation cleared
        (EVICTION_PLANNED, ABSENT),               # canceled (claim gone)
        (EVICTION_DRAINING, ABSENT),              # canceled (claim gone)
        (EVICTION_DEALLOCATED, ABSENT),           # re-placed / failed
    }),
)

# -- defrag moves (active defragmentation, pkg/defrag.py) ---------------------
#
# The defrag controller migrates LIVE claims off shredded free space so
# large sub-tori re-form (the capacity-recovery half of the eviction
# machinery). Each planned move is one record in the controller's
# CheckpointManager, mirroring the eviction ladder so a controller
# crash mid-move resumes from the durable stage:
#
#   absent -> DefragPlanned          (move planned: target devices
#                                     chosen, placement hint stamped)
#   DefragPlanned -> DefragDraining  (consumer pods evicted,
#                                     reservations dropped)
#   DefragDraining -> DefragDeallocated (allocation cleared; the
#                                     scheduler re-places onto the
#                                     hinted target)
#   <any> -> absent                  (re-placed, claim gone, or the
#                                     move aborted at its deadline)
#
# The same stage-skip rule applies: a drain or deallocation without
# its durable intent record is exactly what the runtime validator
# refuses.

DEFRAG_PLANNED = "DefragPlanned"
DEFRAG_DRAINING = "DefragDraining"
DEFRAG_DEALLOCATED = "DefragDeallocated"

DEFRAG_POLICY = TransitionPolicy(
    "defrag",
    frozenset({
        (ABSENT, DEFRAG_PLANNED),                 # move planned
        (DEFRAG_PLANNED, DEFRAG_DRAINING),        # pods evicted
        (DEFRAG_DRAINING, DEFRAG_DEALLOCATED),    # allocation cleared
        (DEFRAG_PLANNED, ABSENT),                 # canceled / aborted
        (DEFRAG_DRAINING, ABSENT),                # canceled / aborted
        (DEFRAG_DEALLOCATED, ABSENT),             # re-placed / aborted
    }),
)

# -- partition lifecycle (pkg/partition/engine.py) ----------------------------
#
# The multi-tenant partition engine persists one record per dynamic
# partition (a PartitionSet-desired carve-out) through the same
# group-committed CheckpointManager, so a node-plugin crash mid-create
# or mid-destroy resumes idempotently:
#
#   absent -> PartitionCreating      (durable intent, carve-out next)
#   PartitionCreating -> PartitionReady      (carve-out realized)
#   PartitionReady -> PartitionDestroying    (last tenant detached /
#                                             profile removed)
#   PartitionCreating -> PartitionDestroying (crash-resume rollback of
#                                             a half-created partition)
#   <Creating|Destroying> -> absent          (create rolled back /
#                                             destroy finished)
#
# A PartitionReady record must never vanish without passing through
# PartitionDestroying: the destroy intent is what makes a crashed
# teardown resumable instead of leaking the carve-out.

PARTITION_CREATING = "PartitionCreating"
PARTITION_READY = "PartitionReady"
PARTITION_DESTROYING = "PartitionDestroying"

# -- autoscale re-plans (serving autoscaler, pkg/autoscale/) ------------------
#
# The demand-driven PartitionSet controller rolls profile re-plans
# through the apiserver as one durable record per re-plan, so a
# controller crash mid-rollout resumes idempotently onto the SAME plan
# (the desired spec is pinned in the Planned record):
#
#   absent -> AutoscalePlanned       (drift past the hysteresis band:
#                                     desired PartitionSet computed and
#                                     pinned durably)
#   AutoscalePlanned -> AutoscaleApplying  (CRD write issued to the
#                                     apiserver)
#   AutoscalePlanned -> absent       (superseded before the write: an
#                                     operator override or fresher plan
#                                     won)
#   AutoscaleApplying -> absent      (CRD content confirmed == plan, or
#                                     an operator override won the race)
#
# A rollout may never skip Planned (an apiserver write without its
# durable intent is unresumable) -- the stage-skip rule the runtime
# validator enforces for every other ladder applies here too.

AUTOSCALE_PLANNED = "AutoscalePlanned"
AUTOSCALE_APPLYING = "AutoscaleApplying"

AUTOSCALE_POLICY = TransitionPolicy(
    "autoscale",
    frozenset({
        (ABSENT, AUTOSCALE_PLANNED),            # durable re-plan intent
        (AUTOSCALE_PLANNED, AUTOSCALE_APPLYING),  # CRD write issued
        (AUTOSCALE_PLANNED, ABSENT),            # superseded pre-write
        (AUTOSCALE_APPLYING, ABSENT),           # confirmed / superseded
    }),
)

# -- cooperative migration (checkpoint-then-switch, pkg/migration.py) --------
#
# The migration controller moves a LIVE claim with workload
# cooperation: the destination window is reserved FIRST, the workload
# is signaled (annotation + CDI env contract) and given a bounded
# window to checkpoint and ack, and only then does the gang drain and
# re-place onto the reserved window. One durable record per in-flight
# move, same group-committed CheckpointManager as every other ladder:
#
#   absent -> MigrationDestReserved    (destination devices chosen and
#                                       reserved; hint stamped)
#   MigrationDestReserved -> MigrationIntentSignaled
#                                      (migration-intent annotation
#                                       stamped; workload now sees the
#                                       signal via its env contract)
#   MigrationIntentSignaled -> MigrationWorkloadAcked
#                                      (workload checkpointed and
#                                       acked within TPU_DRA_MIGRATION_ACK_S)
#   MigrationWorkloadAcked -> MigrationSwitching
#                                      (gang drained, allocation
#                                       cleared; scheduler re-places
#                                       onto the reserved window)
#   <any> -> absent                    (completed -- or ANY failure:
#                                       ack timeout, checkpoint
#                                       failure, destination lost,
#                                       claim gone. Fallback retires
#                                       the record and hands the claim
#                                       to the cold eviction path.)
#
# The per-state escape to absent is load-bearing: EVERY failure mode
# must degrade to the cold path with the reservation released, so no
# reachable state may lack a legal retirement edge (crash_closure_all
# proves exactly that).

MIGRATION_DEST_RESERVED = "MigrationDestReserved"
MIGRATION_INTENT_SIGNALED = "MigrationIntentSignaled"
MIGRATION_WORKLOAD_ACKED = "MigrationWorkloadAcked"
MIGRATION_SWITCHING = "MigrationSwitching"

MIGRATION_POLICY = TransitionPolicy(
    "migration",
    frozenset({
        (ABSENT, MIGRATION_DEST_RESERVED),        # window reserved
        (MIGRATION_DEST_RESERVED,
         MIGRATION_INTENT_SIGNALED),              # workload signaled
        (MIGRATION_INTENT_SIGNALED,
         MIGRATION_WORKLOAD_ACKED),               # checkpoint acked
        (MIGRATION_WORKLOAD_ACKED,
         MIGRATION_SWITCHING),                    # gang drained
        (MIGRATION_DEST_RESERVED, ABSENT),        # fallback / canceled
        (MIGRATION_INTENT_SIGNALED, ABSENT),      # ack timeout fallback
        (MIGRATION_WORKLOAD_ACKED, ABSENT),       # dest lost fallback
        (MIGRATION_SWITCHING, ABSENT),            # re-placed / fallback
    }),
)

PARTITION_POLICY = TransitionPolicy(
    "partition",
    frozenset({
        (ABSENT, PARTITION_CREATING),                  # durable intent
        (PARTITION_CREATING, PARTITION_READY),         # carve-out live
        (PARTITION_CREATING, PARTITION_DESTROYING),    # crash rollback
        (PARTITION_CREATING, ABSENT),                  # create failed
        (PARTITION_READY, PARTITION_DESTROYING),       # teardown intent
        (PARTITION_DESTROYING, ABSENT),                # destroy done
    }),
)

#: Registry for the AST pass (lint TPUDRA007): modules constructing a
#: CheckpointManager must pass transition_policy= explicitly -- one of
#: these, or None with an inline-allow comment stating why.
POLICIES = {
    "two-phase": TWO_PHASE_POLICY,
    "single-phase": SINGLE_PHASE_POLICY,
    "eviction": EVICTION_POLICY,
    "defrag": DEFRAG_POLICY,
    "partition": PARTITION_POLICY,
    "autoscale": AUTOSCALE_POLICY,
    "migration": MIGRATION_POLICY,
}


# -- crash-closure pass -------------------------------------------------------
#
# Every transition commits durably (group-committed checkpoint / CRD
# record), so a crash can land between ANY two writes: each state a
# policy can reach from absent is a state recovery may find on disk.
# The closure proof is therefore pure graph reachability over the
# declared transitions:
#
#   * every state REACHABLE from absent must also REACH absent again
#     (a resume path: the record can always be driven back out of the
#     checkpoint -- completed, rolled back, or canceled). A reachable
#     state with no path back is a wedge: one crash there leaves a
#     record no controller can ever legally retire.
#   * every state a policy NAMES must be reachable from absent --
#     an unreachable state is dead weight in the model (and a tell
#     that a transition row was dropped in an edit).


def crash_closure(policy: TransitionPolicy) -> dict:
    """Prove (or refute) that every on-disk state reachable across a
    crash seam has a legal resume path. Returns a machine-readable
    report: ``{"policy", "states", "unreachable", "unresumable",
    "ok"}`` with states spelled as strings ("absent" for ``None``)."""
    succ: dict[str | None, set[str | None]] = {}
    pred: dict[str | None, set[str | None]] = {}
    states: set[str | None] = {ABSENT}
    for old, new in policy.allowed:
        states.add(old)
        states.add(new)
        succ.setdefault(old, set()).add(new)
        pred.setdefault(new, set()).add(old)

    def closure(start, edges) -> set:
        out = {start}
        stack = [start]
        while stack:
            for nxt in edges.get(stack.pop(), ()):
                if nxt not in out:
                    out.add(nxt)
                    stack.append(nxt)
        return out

    reachable = closure(ABSENT, succ)   # durable-on-disk candidates
    resumable = closure(ABSENT, pred)   # states with a path back out

    def spell(s: str | None) -> str:
        return s if s is not None else "absent"

    unreachable = sorted(
        spell(s) for s in states - reachable if s is not ABSENT)
    unresumable = sorted(
        spell(s) for s in reachable - resumable if s is not ABSENT)
    return {
        "policy": policy.name,
        "states": sorted(spell(s) for s in states),
        "unreachable": unreachable,
        "unresumable": unresumable,
        "ok": not unreachable and not unresumable,
    }


def crash_closure_all(
        policies: dict[str, TransitionPolicy] | None = None) -> dict:
    """Run the closure proof over every registered policy (or a given
    registry). ``{"ok": bool, "policies": {name: report}}``."""
    reports = {name: crash_closure(pol)
               for name, pol in sorted((policies or POLICIES).items())}
    return {"ok": all(r["ok"] for r in reports.values()),
            "policies": reports}
