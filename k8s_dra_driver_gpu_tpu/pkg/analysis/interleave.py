"""Deterministic interleaving explorer -- a targeted ``-race`` analog.

A ``ControlledScheduler`` runs N worker threads ONE at a time: every
instrumented operation (virtual lock acquire, explicit yield point)
hands control back to the scheduler, which picks the next thread to run
from the currently-runnable set. The sequence of picks IS the schedule;
``explore()`` enumerates schedules depth-first (exhaustive on small
state spaces, bounded otherwise) and ``explore_random()`` samples them
with a seeded RNG. After every complete schedule an invariant callback
inspects the end state -- a schedule that violates it is returned with
its full decision trace, i.e. a deterministic reproducer.

Locks are **virtual**: the scheduler tracks ownership and wait queues
itself, so a "blocked" thread never blocks a real OS thread -- which is
what lets the scheduler (a) suspend threads at arbitrary points without
deadlocking the harness and (b) detect true deadlocks (no runnable
thread, not all done) as findings instead of hangs.

``instrument_device_state`` wires the real prepare/unprepare pipeline
into the scheduler: ``Flock`` acquire/release, ``ShardedLocks.hold``,
``DeviceState._lock`` and the ``CheckpointManager`` commit point all
become virtual-lock choice points, so the explorer permutes exactly the
interleavings the locking hierarchy (docs/architecture.md) claims to
make safe.
"""

from __future__ import annotations

import random
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field

from ..flock import Flock, FlockReentrantError

_RUNNABLE = "runnable"
_BLOCKED = "blocked"
_DONE = "done"

# Scheduler <-> worker handoff bound. Generous: a worker doing real
# file I/O between yield points finishes in microseconds; hitting this
# means a worker blocked on something the harness does not control
# (an uninstrumented real lock) -- a harness bug worth a loud error.
_HANDOFF_TIMEOUT_S = 30.0


class DeadlockError(Exception):
    """No runnable thread while some are still blocked: the schedule
    drove the system into a true deadlock. Carries who-waits-on-what."""


class HarnessStallError(Exception):
    """A worker failed to return control: it blocked on something
    uninstrumented. Fix the instrumentation, not the schedule."""


class _ScheduleAborted(BaseException):
    """Internal: unwinds workers parked at a choice point when their
    schedule ends abnormally (deadlock, stall, step cap) so failed
    schedules do not leak a thread each. BaseException on purpose --
    worker code's ``except Exception`` must not swallow the unwind."""


class _Worker:
    __slots__ = ("name", "fn", "thread", "event", "state", "waiting_on",
                 "exc", "started", "aborted", "parked_label")

    def __init__(self, name: str, fn):
        self.name = name
        self.fn = fn
        self.thread: threading.Thread | None = None
        self.event = threading.Event()
        self.state = _RUNNABLE
        self.waiting_on = None
        self.exc: BaseException | None = None
        self.started = False
        self.aborted = False
        # Label of the operation this worker will perform when next
        # scheduled (set at every pause) -- what the partial-order
        # reduction in explore() judges independence on.
        self.parked_label = f"start {name}"


class _VLock:
    __slots__ = ("owner", "waiters", "reentrant_error")

    def __init__(self, reentrant_error: bool = True):
        self.owner: _Worker | None = None
        self.waiters: list[_Worker] = []
        self.reentrant_error = reentrant_error


class VirtualLock:
    """threading.Lock-shaped adapter over a scheduler-managed lock, so
    instrumented code can swap a real mutex for a virtual one."""

    def __init__(self, sched: "ControlledScheduler", lock_id):
        self._sched = sched
        self._id = lock_id

    def acquire(self, timeout: float | None = None, blocking: bool = True):
        self._sched.lock_acquire(self._id)
        return True

    def release(self) -> None:
        self._sched.lock_release(self._id)

    def __enter__(self) -> "VirtualLock":
        self.acquire()  # lock adapter implementation; tpudra: allow=TPUDRA002
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class Chooser:
    """Base chooser: always the first runnable thread."""

    def choose(self, n_options: int) -> int:
        return 0


class ReplayChooser(Chooser):
    """Replays a recorded prefix, then picks option 0 -- the DFS
    workhorse."""

    def __init__(self, prefix: list[int]):
        self.prefix = list(prefix)
        self._pos = 0

    def choose(self, n_options: int) -> int:
        if self._pos < len(self.prefix):
            pick = self.prefix[self._pos]
            self._pos += 1
            return min(pick, n_options - 1)
        return 0


class RandomChooser(Chooser):
    def __init__(self, rng: random.Random):
        self.rng = rng

    def choose(self, n_options: int) -> int:
        return self.rng.randrange(n_options)


class ControlledScheduler:
    def __init__(self, chooser: Chooser | None = None):
        self._chooser = chooser or Chooser()
        self._workers: list[_Worker] = []
        self._by_ident: dict[int, _Worker] = {}
        self._locks: dict = {}
        self._wake = threading.Event()
        self._started = False
        #: [(n_options, chosen_index)] -- the schedule's identity.
        self.choice_log: list[tuple[int, int]] = []
        #: Per choice-log entry: the label of the operation each option
        #: stands for (a runnable worker's parked op for scheduling
        #: choices, ``label[i]`` for value choices). explore()'s
        #: partial-order reduction consumes this.
        self.option_log: list[list[str]] = []
        #: [(worker name, label)] -- human-readable decision trace.
        self.trace: list[tuple[str, str]] = []

    # -- driver side ----------------------------------------------------------

    def spawn(self, fn, name: str | None = None) -> None:
        if self._started:
            raise RuntimeError("spawn() after run() started")
        self._workers.append(_Worker(name or f"t{len(self._workers)}", fn))

    def run(self, max_steps: int = 100_000) -> "ControlledScheduler":
        """Drive all workers to completion under one schedule."""
        self._started = True
        for w in self._workers:
            w.thread = threading.Thread(
                target=self._worker_main, args=(w,), name=w.name,
                daemon=True,
            )
            w.thread.start()
        steps = 0
        while True:
            runnable = [w for w in self._workers if w.state == _RUNNABLE]
            if not runnable:
                blocked = [w for w in self._workers if w.state == _BLOCKED]
                if not blocked:
                    break  # all done
                msg = (
                    "deadlock: "
                    + "; ".join(
                        f"{w.name} waits on {w.waiting_on!r}"
                        for w in blocked
                    )
                    + " | held: "
                    + ", ".join(
                        f"{lid!r} by {vl.owner.name}"
                        for lid, vl in self._locks.items()
                        if vl.owner is not None
                    )
                )
                self._abort_parked()
                raise DeadlockError(msg)
            steps += 1
            if steps > max_steps:
                self._abort_parked()
                raise HarnessStallError(
                    f"schedule exceeded {max_steps} steps"
                )
            idx = self._chooser.choose(len(runnable))
            idx = max(0, min(idx, len(runnable) - 1))
            self.choice_log.append((len(runnable), idx))
            self.option_log.append([w.parked_label for w in runnable])
            worker = runnable[idx]
            self._wake.clear()
            worker.event.set()
            if not self._wake.wait(timeout=_HANDOFF_TIMEOUT_S):
                self._abort_parked()
                raise HarnessStallError(
                    f"worker {worker.name} did not return control "
                    f"within {_HANDOFF_TIMEOUT_S}s (blocked on an "
                    "uninstrumented primitive?)"
                )
        return self

    def _abort_parked(self) -> None:
        """Unwind every not-yet-done worker before an abnormal schedule
        end: without this each deadlocking schedule would leak its
        blocked threads parked on worker.event.wait() forever -- a DFS
        that finds hundreds of deadlocks (the tool's purpose) would
        drown the process in stuck daemon threads."""
        for w in self._workers:
            if w.state != _DONE:
                w.aborted = True
                w.event.set()

    @property
    def errors(self) -> list[BaseException]:
        return [w.exc for w in self._workers if w.exc is not None]

    @property
    def choices(self) -> list[int]:
        return [c for _, c in self.choice_log]

    # -- worker side ----------------------------------------------------------

    def _worker_main(self, worker: _Worker) -> None:
        self._by_ident[threading.get_ident()] = worker
        worker.event.wait()
        worker.event.clear()
        try:
            if worker.aborted:
                raise _ScheduleAborted
            worker.fn()
        except _ScheduleAborted:
            pass  # harness unwind, not a workload error
        except BaseException as e:  # noqa: BLE001 - reported to driver
            worker.exc = e
        finally:
            # Release anything the worker still owns so one failed
            # thread doesn't wedge the rest of the schedule.
            for vl in self._locks.values():
                if vl.owner is worker:
                    vl.owner = None
                    for w in vl.waiters:
                        w.state = _RUNNABLE
                    vl.waiters.clear()
            worker.state = _DONE
            self._wake.set()

    def _current(self) -> _Worker | None:
        return self._by_ident.get(threading.get_ident())

    def _pause(self, worker: _Worker, label: str) -> None:
        self.trace.append((worker.name, label))
        worker.parked_label = label
        self._wake.set()
        worker.event.wait()
        worker.event.clear()
        if worker.aborted:
            raise _ScheduleAborted

    def yield_point(self, label: str = "") -> None:
        """A schedule choice point. No-op from uninstrumented threads,
        so instrumented library code stays usable outside the
        explorer."""
        worker = self._current()
        if worker is not None:
            self._pause(worker, label or "yield")

    def choice(self, n: int, label: str = "choice") -> int:
        """A VALUE choice point: the worker asks the schedule to pick
        one of ``n`` modeled outcomes (deliver vs. delay a watch event,
        crash vs. survive a fault seam, ...). The pick lands in the
        same ``choice_log`` as scheduling decisions, so DFS sibling
        enumeration, replay, and minimization all treat modeled
        nondeterminism and thread interleaving uniformly.

        Runs inline in the worker (no scheduler handoff): exactly one
        worker executes at a time, so appending to the logs here is
        race-free. From an uninstrumented thread the first option is
        taken, keeping instrumented code usable outside the explorer.
        """
        if n <= 1:
            return 0
        worker = self._current()
        if worker is None:
            return 0
        idx = self._chooser.choose(n)
        idx = max(0, min(idx, n - 1))
        self.choice_log.append((n, idx))
        # Every option of a value choice belongs to THIS worker: tag
        # them with the worker name so independence judgments never
        # commute two options of one program order.
        self.option_log.append(
            [f"{worker.name}:{label}[{i}]" for i in range(n)])
        self.trace.append((worker.name, f"{label}={idx}"))
        return idx

    def lock_acquire(self, lock_id, reentrant_error: bool = True) -> None:
        worker = self._current()
        if worker is None:
            return  # uninstrumented thread: scheduler not in control
        self._pause(worker, f"acquire {lock_id!r}")
        vl = self._locks.setdefault(lock_id, _VLock(reentrant_error))
        if vl.owner is worker:
            if vl.reentrant_error:
                raise FlockReentrantError(
                    f"{worker.name} re-acquired virtual lock {lock_id!r}"
                )
            return
        while vl.owner is not None:
            worker.state = _BLOCKED
            worker.waiting_on = lock_id
            vl.waiters.append(worker)
            self._pause(worker, f"blocked {lock_id!r}")
            # Woken: we are runnable again; the lock may have been
            # re-taken by a thread scheduled before us -- re-check.
        worker.waiting_on = None
        vl.owner = worker

    def lock_release(self, lock_id) -> None:
        worker = self._current()
        if worker is None:
            return
        vl = self._locks.get(lock_id)
        if vl is None or vl.owner is not worker:
            return  # release of a lock taken outside scheduler control
        vl.owner = None
        for w in vl.waiters:
            w.state = _RUNNABLE
        vl.waiters.clear()


# -- exploration --------------------------------------------------------------


@dataclass
class ScheduleFailure:
    choices: list[int]
    error: BaseException
    trace: list[tuple[str, str]]

    def __str__(self) -> str:
        steps = " -> ".join(f"{n}:{lbl}" for n, lbl in self.trace)
        return (f"schedule {self.choices} failed: "
                f"{type(self.error).__name__}: {self.error}\n  {steps}")


@dataclass
class ExplorationResult:
    schedules_run: int = 0
    failures: list[ScheduleFailure] = field(default_factory=list)
    #: True when the DFS drained every branch: the run was exhaustive.
    exhausted: bool = False

    @property
    def ok(self) -> bool:
        return not self.failures


def _run_one(build, invariant, chooser, cleanup=None) -> tuple[
        ControlledScheduler, BaseException | None]:
    sched = ControlledScheduler(chooser)
    build(sched)
    err: BaseException | None = None
    try:
        sched.run()
        if sched.errors:
            err = sched.errors[0]
    except (DeadlockError, AssertionError, HarnessStallError) as e:
        err = e
    finally:
        # Cleanup runs after EVERY schedule (also failed ones): it is
        # where instrumentation contexts unpatch, so one bad schedule
        # cannot leak monkeypatches into the next.
        if cleanup is not None:
            try:
                cleanup(sched)
            except BaseException as e:  # noqa: BLE001 - surfaced below
                err = err or e
    if err is None and invariant is not None:
        try:
            invariant(sched)
        except Exception as e:  # noqa: BLE001 - any violation is a finding
            # Not just AssertionError: the worst violations surface as
            # e.g. CheckpointCorruptError from re-parsing the file --
            # they must become ScheduleFailures with a reproducer, not
            # abort the whole exploration.
            err = e
    return sched, err


def explore(build, invariant=None, max_schedules: int = 1000,
            stop_at_first_failure: bool = False,
            cleanup=None, independent=None) -> ExplorationResult:
    """Depth-first systematic exploration.

    ``build(sched)`` spawns the worker threads (fresh state per
    schedule!); ``invariant(sched)`` raises AssertionError on a
    violated end-state; ``cleanup(sched)`` always runs after each
    schedule (unpatch instrumentation there). Worker exceptions and
    deadlocks count as failures too (workers that EXPECT errors must
    catch them and fold the outcome into state the invariant judges).

    ``independent(op_a, op_b)`` enables a sleep-set-style partial-order
    reduction: at each decision point the sibling branch that would run
    ``op_a`` instead of the chosen ``op_b`` is pruned when the callback
    judges the two operation labels independent (commuting: disjoint
    state, neither enables/disables the other). The labels are the
    ``option_log`` strings (a worker's parked-op label, or
    ``worker:label[i]`` for value choices). The reduction is only sound
    for genuinely commuting operations -- when unsure return False; see
    docs/analysis.md "POR caveats".
    """
    result = ExplorationResult()
    pending: list[list[int]] = [[]]
    seen: set[tuple[int, ...]] = set()
    while pending and result.schedules_run < max_schedules:
        prefix = pending.pop()
        sched, err = _run_one(build, invariant, ReplayChooser(prefix),
                              cleanup)
        result.schedules_run += 1
        if err is not None:
            result.failures.append(ScheduleFailure(
                choices=sched.choices, error=err, trace=sched.trace))
            if stop_at_first_failure:
                return result
        # Enqueue every unexplored sibling at/beyond the replayed
        # prefix (standard stateless-model-checking DFS frontier).
        log = sched.choice_log
        ops = sched.option_log
        for pos in range(len(prefix), len(log)):
            n_options, chosen = log[pos]
            step_ops = ops[pos] if pos < len(ops) else None
            for alt in range(n_options):
                if alt == chosen:
                    continue
                if independent is not None and step_ops is not None \
                        and len(step_ops) == n_options and independent(
                            step_ops[alt], step_ops[chosen]):
                    # Commuting ops: running alt first reaches the same
                    # state this branch reaches one step later -- the
                    # sibling adds schedules, not coverage.
                    continue
                branch = [c for _, c in log[:pos]] + [alt]
                key = tuple(branch)
                if key not in seen:
                    seen.add(key)
                    pending.append(branch)
    result.exhausted = not pending
    return result


# Frontier-tracking bookkeeping cap for explore_random: past this many
# discovered branches the space is plainly not small enough to prove
# exhausted, so the accounting (the only thing the cap bounds) stops.
_RANDOM_FRONTIER_CAP = 100_000


def explore_random(build, invariant=None, schedules: int = 100,
                   seed: int = 0, cleanup=None) -> ExplorationResult:
    """Seeded-random schedule sampling -- the cheap wide net for state
    spaces too big to exhaust.

    Keeps the same branch-frontier accounting as ``explore()``: every
    executed schedule covers the discovered branch prefixes it extends,
    and when the frontier provably drains (every discovered branch is
    covered -- small state spaces) the run reports ``exhausted=True``
    and short-circuits instead of burning the remaining samples on
    schedules it has already seen.
    """
    result = ExplorationResult()
    rng = random.Random(seed)
    # Branch prefixes discovered but not yet extended by any executed
    # schedule -- explore()'s `pending`, fed by random runs instead of
    # a DFS pop. `seen` mirrors explore()'s dedup (and includes the
    # root, covered by the very first run).
    pending: set[tuple[int, ...]] = {()}
    seen: set[tuple[int, ...]] = {()}
    tracking = True
    for _ in range(schedules):
        sched, err = _run_one(build, invariant, RandomChooser(rng),
                              cleanup)
        result.schedules_run += 1
        if err is not None:
            result.failures.append(ScheduleFailure(
                choices=sched.choices, error=err, trace=sched.trace))
        if not tracking:
            continue
        log = sched.choice_log
        run = tuple(c for _, c in log)
        for pos, (n_options, chosen) in enumerate(log):
            for alt in range(n_options):
                if alt == chosen:
                    continue
                branch = run[:pos] + (alt,)
                if branch not in seen:
                    seen.add(branch)
                    pending.add(branch)
        for i in range(len(run) + 1):
            pending.discard(run[:i])
        if len(seen) > _RANDOM_FRONTIER_CAP:
            tracking = False  # too big to prove exhausted; keep sampling
            pending.clear()
        elif not pending:
            result.exhausted = True
            break
    return result


# -- DeviceState instrumentation ----------------------------------------------


class _VFlockGuard:
    __slots__ = ("_sched", "_id", "_flock")

    def __init__(self, sched, lock_id, flock):
        self._sched = sched
        self._id = lock_id
        self._flock = flock

    def __enter__(self):
        return self._flock

    def __exit__(self, *exc) -> None:
        self._sched.lock_release(self._id)


@contextmanager
def instrument_device_state(sched: ControlledScheduler, state,
                            fast_io: bool = True):
    """Route every lock in a DeviceState's prepare/unprepare pipeline
    through ``sched``'s virtual locks, and make the checkpoint commit
    point a deterministic choice point.

    - ``Flock.acquire/release`` (class-wide, keyed by lock-file path):
      covers the reservation ``pu.lock``, the checkpoint flock, and the
      sub-slice registry flock. Re-entrant virtual acquisition raises
      the real ``FlockReentrantError``, preserving fail-fast fidelity.
    - ``state._lock`` / ``ShardedLocks.hold``: virtual mutex / sorted
      virtual shard set.
    - ``CheckpointManager._submit``: the group-commit condition-variable
      machinery is inherently timing-driven, so under the explorer each
      commit applies directly under the (virtual) checkpoint flock --
      same mutation + durability semantics, deterministic schedule.
    - ``fast_io``: stubs ``os.fsync``/``os.fdatasync`` for the duration
      of the context -- PROCESS-WIDE, unlike the lock hooks below;
      consistency is judged by re-parsing the file, not by crash
      durability. Leave it off if anything else in the process needs
      real durability while the exploration runs.

    The lock/commit hooks only affect threads spawned on ``sched``:
    from uninstrumented threads every hook falls through to the
    original implementation.
    """
    import os as _os

    orig_acquire = Flock.acquire
    orig_release = Flock.release

    def v_acquire(self, timeout: float = 10.0, poll_interval: float = 0.01,
                  cancel=None):
        if sched._current() is None:
            return orig_acquire(self, timeout=timeout,
                                poll_interval=poll_interval, cancel=cancel)
        lock_id = ("flock", self._path)
        sched.lock_acquire(lock_id)  # raises FlockReentrantError on re-entry
        return _VFlockGuard(sched, lock_id, self)

    def v_release(self) -> None:
        if sched._current() is None:
            return orig_release(self)
        sched.lock_release(("flock", self._path))

    checkpoint = state._checkpoint
    orig_submit = type(checkpoint)._submit

    def v_submit(self, fn, dirty_uids, timer=None):
        if sched._current() is None:
            return orig_submit(self, fn, dirty_uids, timer=timer)
        with self._lock.acquire(timeout=10.0):  # virtual via v_acquire
            try:
                cp = self._read_locked()
                self._apply_one_locked(cp, fn, dirty_uids)
                self._write_locked(cp)
            except BaseException:
                self._cp = None
                self._sig = None
                self._invalidate_frags(None)
                raise

    shards = state._shards
    orig_hold = type(shards).hold

    @contextmanager
    def v_hold(self, shard_ids, timer=None):
        if sched._current() is None:
            with orig_hold(self, shard_ids, timer):
                yield
            return
        ordered = sorted(set(shard_ids))
        taken = []
        try:
            for s in ordered:
                sched.lock_acquire(("shard", s), reentrant_error=False)
                taken.append(s)
            yield
        finally:
            for s in reversed(taken):
                sched.lock_release(("shard", s))

    orig_state_lock = state._lock
    orig_fsync = _os.fsync
    orig_fdatasync = _os.fdatasync
    try:
        Flock.acquire = v_acquire
        Flock.release = v_release
        type(checkpoint)._submit = v_submit
        type(shards).hold = v_hold
        state._lock = VirtualLock(sched, ("mutex", "device_state"))
        if fast_io:
            _os.fsync = lambda fd: None
            _os.fdatasync = lambda fd: None
        yield sched
    finally:
        Flock.acquire = orig_acquire
        Flock.release = orig_release
        type(checkpoint)._submit = orig_submit
        type(shards).hold = orig_hold
        state._lock = orig_state_lock
        _os.fsync = orig_fsync
        _os.fdatasync = orig_fdatasync
