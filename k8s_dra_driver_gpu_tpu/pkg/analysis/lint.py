"""AST lock-hierarchy + cache-discipline linter (flake8-style runner).

Encodes the concurrency invariants docs/architecture.md documents but
nothing previously enforced. Rules carry stable IDs:

- **TPUDRA001** lock-hierarchy order: acquiring an outer (lower-level)
  lock while a narrower one is held. The documented order is
  1. global reservation (``pu_lock`` flock) -> 2. per-chip shard locks
  (``ShardedLocks.hold``) -> 3. checkpoint group commit
  (``CheckpointManager`` calls). Taking level 1 inside level 2 (etc.)
  is the deadlock shape the hierarchy exists to prevent.
- **TPUDRA002** unguarded lock acquire: a ``.acquire(...)`` whose guard
  is discarded, or that has no ``.release()``/``__exit__`` reachable
  from a ``finally`` in the same function. Locks must be held through
  ``with`` or an explicit try/finally.
- **TPUDRA003** blocking call under a shard lock or flock: kube API
  verbs, ``time.sleep``, and subprocess waits inside a
  ``with <shards>.hold(...)`` / ``with <flock>.acquire(...)`` body
  park every same-shard claim (and, for the flock, every process on
  the node) behind one slow RPC.
- **TPUDRA004** re-entrant flock acquire: lexically re-acquiring a
  flock already held by the enclosing ``with`` -- guaranteed
  ``FlockReentrantError`` at runtime.
- **TPUDRA005** raw claim-state literal: ``"PrepareStarted"`` /
  ``"PrepareCompleted"`` string literals outside the enum/model
  definition sites bypass the state machine's single source of truth.
- **TPUDRA006** cached-API-object mutation: in-place mutation of an
  object obtained from an informer cache or a kube client (or of an
  API-object parameter) without a deep copy first -- the client-go
  "never mutate informer objects" rule.
- **TPUDRA007** unmodeled checkpoint manager: constructing a
  ``CheckpointManager`` without an explicit ``transition_policy=``
  keyword opts the call site out of the checkpoint state-machine
  validator silently.
- **TPUDRA008** raw kube client: constructing ``KubeClient`` outside a
  ``RetryingKubeClient(...)`` wrap (pkg/retry.py) hands production code
  a client with no backoff, no deadline discipline, and no circuit
  breaker; kube verb calls on such a raw client without an explicit
  ``timeout=`` are flagged too (they park threads on the urllib
  default when the apiserver wedges).
- **TPUDRA009** scheduler sync path lists a watched resource straight
  off the kube client: inside pkg/scheduler.py every read of a watched
  resource (pods, claims, slices, classes, CDs, ...) must go through
  the informer-backed ClusterView / inventory snapshot
  (pkg/schedcache.py) -- a raw ``kube.list`` there reintroduces the
  O(cluster)-per-tick full resync the incremental scheduler exists to
  remove.

Suppression: per-line ``# tpudra: allow=TPUDRA002[,TPUDRA003] reason``
comments, or the committed baseline file (``analysis-baseline.json``)
keyed by stable line-number-free fingerprints.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field

RULES: dict[str, str] = {
    "TPUDRA000": "file could not be parsed (syntax error)",
    "TPUDRA001": "lock acquired out of documented hierarchy order",
    "TPUDRA002": "lock acquire without with-guard or release in finally",
    "TPUDRA003": "blocking I/O / kube API call while holding a shard "
                 "lock or flock",
    "TPUDRA004": "re-entrant flock acquire (FlockReentrantError at "
                 "runtime)",
    "TPUDRA005": "raw claim-state string literal bypasses the "
                 "ClaimState enum / state-machine model",
    "TPUDRA006": "in-place mutation of an informer-cached / kube API "
                 "object without deep copy",
    "TPUDRA007": "CheckpointManager constructed without an explicit "
                 "transition_policy",
    "TPUDRA008": "raw KubeClient outside the RetryingKubeClient "
                 "wrapper (or kube call without an explicit timeout)",
    "TPUDRA009": "scheduler sync path lists a watched resource via the "
                 "raw kube client instead of the informer-backed "
                 "ClusterView/snapshot (pkg/schedcache), or mutates "
                 "per-pool sub-snapshot internals outside "
                 "pkg/schedcache.py's delta paths",
    "TPUDRA010": "blocking kube I/O while holding the scheduler "
                 "registry lock (_state_lock) or the allocation-state "
                 "lock; commit I/O is sanctioned under per-node locks "
                 "only (sharded-allocation hierarchy)",
    "TPUDRA011": "sub-slice carve-out create/destroy outside the "
                 "partition engine / DeviceState lock discipline: "
                 "registry mutations must go through "
                 "pkg/partition/engine.py (holder-counted, durable "
                 "partition records) or kubeletplugin/device_state.py "
                 "(claim-checkpointed), never ad hoc",
    "TPUDRA012": "span / flight-recorder entry created outside the "
                 "public with-guarded API: bare Span(...) or "
                 "FlightEvent(...) construction, or start_span() "
                 "outside a with statement, leaks an unfinished span "
                 "(never exported, wrong parent for everything after "
                 "it on the thread) -- use tracing.span(...) / "
                 "FlightRecorder.record(...)",
    "TPUDRA013": "telemetry ring / fleet-aggregator mutation outside "
                 "the telemetry layer: record_sample(...) / fold_*(...) "
                 "calls are fenced to pkg/fleetstate.py, pkg/anomaly.py "
                 "and kubeletplugin/health.py -- every other producer "
                 "goes through the health-poll sampling seam or the "
                 "public FleetAggregator.observe_pass entry, so the "
                 "bounded time-series can't be corrupted (or "
                 "double-fed) from a random call site",
    "TPUDRA014": "PartitionSet spec/profile mutation outside the "
                 "autoscale control plane: PartitionSet(...) / "
                 "PartitionProfile(...) construction and apiserver "
                 "writes to the partitionsets CRD are fenced to "
                 "pkg/autoscale/ and the pkg/partition/spec.py "
                 "definition site -- every other producer consumes "
                 "plans through the CRD watch / engine apply path, so "
                 "a random call site can never fork the fleet's "
                 "desired layout from the controller's durable "
                 "rollout records",
    "TPUDRA015": "power-budget / pre-warm state mutation outside its "
                 "definition site: AllocationState.power_debit/"
                 "power_credit are fenced to pkg/schedcache.py (the "
                 "per-node power ledger must stay balanced against "
                 "try_commit's atomic judgment) and "
                 "PartitionEngine.set_prewarm to the engine + the "
                 "node driver's CRD-watch path (the warm carve-out "
                 "set must track the forecaster's hint, never a "
                 "random call site)",
    "TPUDRA016": "cached API object mutated through a cross-module "
                 "helper (call-graph resolved): the callee writes "
                 "through its parameter, so the call site mutates an "
                 "informer-cached object exactly like an in-place "
                 "store -- deep-copy before the call, or move the "
                 "mutation into the object's owning module",
    "TPUDRA017": "kube I/O or sleep reached TRANSITIVELY while "
                 "holding _state_lock/_alloc_lock/shard locks/a "
                 "flock (call-graph closure): the witness edge chain "
                 "shows which helper smuggled the blocking call under "
                 "the lock (the direct case is TPUDRA003/010)",
    "TPUDRA018": "kube write to resourceclaims inside a "
                 "commit-protocol scope (a function that couples "
                 "AllocationState.try_commit with apiserver writes) "
                 "whose payload never rides a resourceVersion "
                 "precondition: without the 409 arbiter, two "
                 "schedulers' commit-then-observe writes can "
                 "double-allocate across processes",
}

#: Doc anchors for CI annotations: rule -> URL. The base is overridable
#: (TPU_DRA_ANALYSIS_DOC_BASE) so hosted CI can point at a rendered
#: docs site; default is the repo-relative markdown anchor.


def rule_doc_url(rule: str) -> str:
    base = os.environ.get("TPU_DRA_ANALYSIS_DOC_BASE",
                          "docs/analysis.md")
    return f"{base}#{rule.lower()}"

# Lock model (docs/architecture.md "Locking hierarchy"). Matched on the
# unparsed base expression of an acquisition.
_LEVEL_RESERVATION = 1
_LEVEL_SHARD = 2
_LEVEL_CHECKPOINT = 3
# Scheduler sharded-allocation hierarchy (docs/architecture.md
# "Sharded allocation locking"): per-node locks (outermost, commit I/O
# sanctioned) -> registry _state_lock (brief bookkeeping) ->
# AllocationState._alloc_lock (innermost, pure state). Distinct level
# band so the prepare-pipeline model never cross-talks.
_LEVEL_SCHED_NODE = 11
_LEVEL_SCHED_STATE = 12
_LEVEL_SCHED_ALLOC = 13
_SCHED_LOCK_FAMILIES = ("sched_state", "sched_alloc")

_KUBE_VERBS = {"get", "list", "patch", "create", "delete", "update",
               "watch"}
_CHECKPOINT_CALLS = {"update", "update_claim", "get"}
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "popitem",
             "clear", "update", "setdefault", "sort", "reverse", "add",
             "discard"}
_META_KEYS = {"metadata", "spec", "status"}
# Files allowed to spell the state literals: the enum definition, the
# declarative model, and this linter's own rule table.
_STATE_LITERAL_FILES = {"checkpoint.py", "statemachine.py", "lint.py"}
# Files allowed to construct a raw KubeClient: the client's own module
# and the retry wrapper that sanctions it (TPUDRA008 scope).
_RAW_KUBECLIENT_FILES = {"kubeclient.py", "retry.py"}
# TPUDRA009 scope: the scheduler's sync paths (the ClusterView in
# schedcache.py is the sanctioned listing layer and is out of scope).
_SCHED_SYNC_FILES = {"scheduler.py"}
# TPUDRA009 sub-snapshot fence: the per-pool incremental snapshot's
# internals (pkg/schedcache PoolSnapshot / InventorySnapshot merged
# indexes + memos) are shared BY IDENTITY across snapshot generations
# -- an external mutation corrupts every generation holding the
# object, silently, for untouched pools. Only schedcache.py's delta
# paths may mutate them; consumers go through the read surface and
# the order_memo_get/put accessors. Rel-path sanctioned (the TPUDRA011
# lesson): a stray schedcache.py elsewhere gets no pass.
_SNAPSHOT_INTERNAL_ATTRS = {
    "by_key", "by_node", "pool_generations", "counter_seeds",
    "sel_cache", "_sel_cache", "order_cache", "slice_sigs",
    "delta_pools", "_pools_of_node", "candidates",
}
_SNAPSHOT_MUT_SUFFIXES = ("pkg/schedcache.py", "analysis/lint.py")
# TPUDRA010 / sched-lock-hierarchy scope: the modules that define and
# use the sharded-allocation locks.
_SCHED_LOCK_FILES = {"scheduler.py", "schedcache.py"}
# TPUDRA011 scope: the ONLY modules sanctioned to mutate the live
# carve-out registry. device_state.py owns claim-driven creates/
# destroys (under the claim's checkpoint + shard locks); the partition
# engine owns partition-record-driven ones (rel-path matched so a
# stray same-named engine.py elsewhere is not sanctioned).
_CARVEOUT_FILES = {"device_state.py"}
_CARVEOUT_REL_SUFFIXES = ("pkg/partition/engine.py",)
# TPUDRA012 scope: the tracing layer itself constructs Spans and may
# hold start_span() results across non-lexical lifetimes (SegmentTimer
# owns its operation span from __init__ to done()); the flight
# recorder constructs its own events. Everyone else goes through
# tracing.span(...) / FlightRecorder.record(...).
_SPAN_CTOR_FILES = {"tracing.py", "lint.py"}
_START_SPAN_FILES = {"tracing.py", "timing.py", "lint.py"}
_FLIGHT_EVENT_FILES = {"flightrecorder.py", "lint.py"}
# TPUDRA013 scope: the telemetry layer's definition sites. The ring /
# aggregator mutation methods are deliberately named record_sample /
# fold_* in pkg/fleetstate.py so the textual match is unambiguous;
# kubeletplugin/health.py is the ONE sanctioned producer (the
# health-poll sampling seam) and pkg/anomaly.py folds its own detector
# state. Rel-path suffixes, not basenames (the TPUDRA011 lesson): a
# stray future health.py elsewhere gets no pass.
_TELEMETRY_MUT_SUFFIXES = ("pkg/fleetstate.py", "pkg/anomaly.py",
                           "kubeletplugin/health.py",
                           "analysis/lint.py")
# TPUDRA014 scope: PartitionSet/PartitionProfile specs are BUILT only
# by the definition site (pkg/partition/spec.py: from_dict/from_file)
# and the autoscale control plane (pkg/autoscale/: the planner emits
# desired sets, the controller writes them to the partitionsets CRD).
# Rel-path sanctioned like TPUDRA011/013 -- a stray spec.py elsewhere
# gets no pass; the pkg/autoscale/ entry is a directory prefix.
_PARTITION_SPEC_SUFFIXES = ("pkg/partition/spec.py",
                            "analysis/lint.py")
_PARTITION_SPEC_DIRS = ("pkg/autoscale/",)
_PARTITION_CRD_WRITE_VERBS = {"create", "update", "patch", "delete"}
# TPUDRA015 scope (rel-path sanctioned like TPUDRA011/013/014): the
# power ledger's debit/credit pair lives on AllocationState and is
# called only from its own apply/release/retarget paths; the pre-warm
# warm-set mutation (set_prewarm) is called only by the engine's
# definition site and the node driver's CRD-watch path
# (Driver.apply_prewarm). A stray same-named file elsewhere gets no
# pass.
_POWER_MUT_SUFFIXES = ("pkg/schedcache.py", "analysis/lint.py")
_PREWARM_MUT_SUFFIXES = ("pkg/partition/engine.py",
                         "kubeletplugin/driver.py",
                         "analysis/lint.py")
# Resources the scheduler watches (mirror of
# pkg/schedcache.WATCHED_RESOURCES, kept literal so the linter has no
# runtime import of the code under analysis).
_WATCHED_RESOURCES = {
    "pods", "nodes", "daemonsets", "jobs", "resourceclaims",
    "resourceslices", "deviceclasses", "resourceclaimtemplates",
    "computedomains",
}
_STATE_LITERALS = {"PrepareStarted", "PrepareCompleted",
                   # Eviction lifecycle (pkg/recovery.py): raw literals
                   # outside the declarative model bypass the eviction
                   # TransitionPolicy exactly like raw claim states.
                   "EvictionPlanned", "EvictionDraining",
                   "EvictionDeallocated",
                   # Defrag-move lifecycle (pkg/defrag.py): the active
                   # defragmentation controller's records live under
                   # the defrag TransitionPolicy; raw literals bypass
                   # it the same way.
                   "DefragPlanned", "DefragDraining",
                   "DefragDeallocated",
                   # Partition lifecycle (pkg/partition/engine.py):
                   # same rule for the partition TransitionPolicy.
                   "PartitionCreating", "PartitionReady",
                   "PartitionDestroying",
                   # Autoscale rollout lifecycle (pkg/autoscale/
                   # controller.py): the serving autoscaler's re-plan
                   # records live under the autoscale TransitionPolicy.
                   "AutoscalePlanned", "AutoscaleApplying",
                   # Cooperative-migration lifecycle (pkg/migration.py):
                   # checkpoint-then-switch records live under the
                   # migration TransitionPolicy; raw literals bypass
                   # the model identically.
                   "MigrationDestReserved", "MigrationIntentSignaled",
                   "MigrationWorkloadAcked", "MigrationSwitching"}
# Copy constructors that launder taint (deep or top-level).
_COPY_CALLS = {"json_copy", "deepcopy", "dict", "list", "sorted",
               "json_loads"}

_ALLOW_RE = re.compile(r"#.*?tpudra:\s*allow=([A-Z0-9,\*]+)")
# Module-wide allow (for server-side fakes that legitimately own and
# mutate the stored API objects): a comment `tpudra: allow-file=<RULE>`
# in the module's HEADER -- the first _FILE_ALLOW_LINES lines only, so
# a stray string literal (or pasted example) deep in a module can
# never silently disable a rule for the whole file. (Spelled with
# <RULE> here so this very comment cannot allow-file the linter
# itself.)
_FILE_ALLOW_RE = re.compile(r"#.*?tpudra:\s*allow-file=([A-Z0-9,\*]+)")
_FILE_ALLOW_LINES = 10


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    qualname: str
    message: str
    key: str
    baselined: bool = False
    #: For interprocedural findings (TPUDRA016/017): the rendered
    #: call-graph witness chain that triggered the rule, e.g.
    #: ``a -> b -> c [self.kube.patch@L12]``. None for local rules.
    edge: str | None = None

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity: survives reformatting, moves with
        the enclosing function."""
        return f"{self.rule}:{self.path}:{self.qualname}:{self.key}"

    @property
    def doc_url(self) -> str:
        return rule_doc_url(self.rule)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "qualname": self.qualname,
            "message": self.message,
            "fingerprint": self.fingerprint,
            "baselined": self.baselined,
            "doc_url": self.doc_url,
            "edge": self.edge,
        }

    def __str__(self) -> str:
        tag = " [baselined]" if self.baselined else ""
        via = f"\n    via {self.edge}" if self.edge else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.message}{tag}{via}")


@dataclass
class LintReport:
    findings: list[Finding] = field(default_factory=list)
    files_scanned: int = 0

    @property
    def active(self) -> list[Finding]:
        return [f for f in self.findings if not f.baselined]

    @property
    def baselined(self) -> list[Finding]:
        return [f for f in self.findings if f.baselined]

    def counts(self, include_baselined: bool = False) -> dict[str, int]:
        out = {rule: 0 for rule in RULES}
        for f in (self.findings if include_baselined else self.active):
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "files_scanned": self.files_scanned,
            "rules": RULES,
            "rule_docs": {rule: rule_doc_url(rule) for rule in RULES},
            "counts": self.counts(),
            "baselined_counts": {
                rule: n for rule, n in (
                    (r, sum(1 for f in self.baselined if f.rule == r))
                    for r in RULES
                ) if n
            },
            "findings": [f.to_dict() for f in self.findings],
        }


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 - diagnostics only
        return "<expr>"


def _attr_chain(node: ast.AST) -> list[str]:
    """['self', 'kube', 'list'] for self.kube.list; [] if not a plain
    name/attribute chain (calls/subscripts break the chain)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def _root_name(node: ast.AST) -> str | None:
    """The root variable of an expression chain, looking through
    attributes, subscripts, and .get()/_meta()-style call wrappers."""
    while True:
        if isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute):
                node = node.func.value
            elif isinstance(node.func, ast.Name) and node.args:
                # helper(obj) -- derive through the first argument
                node = node.args[0]
            else:
                return None
        elif isinstance(node, ast.Name):
            return node.id
        else:
            return None


@dataclass
class _Held:
    family: str  # "flock" | "shard"
    level: int | None
    key: str  # normalized base-expression source
    line: int


class _FuncState:
    def __init__(self, qualname: str):
        self.qualname = qualname
        self.tainted: set[str] = set()
        # Base expressions released inside a finally; True = wildcard
        # (an __exit__ call, which may cover any guard).
        self.released_in_finally: set[str] = set()
        self.exit_in_finally = False
        self.api_params: set[str] = set()
        # Locals bound to a RAW (unwrapped) KubeClient(...): verb calls
        # on them without an explicit timeout are TPUDRA008 findings.
        self.raw_kube: set[str] = set()
        # TPUDRA018 (commit-protocol scope): the function couples an
        # AllocationState.try_commit reservation with apiserver writes.
        self.commit_scope = False
        # ... and whether any payload construction in it touches a
        # "resourceVersion" key (the precondition riding the write).
        self.rv_literal = False
        # Deferred kube writes to resourceclaims: judged when the
        # function closes (the rv literal may appear after the call).
        self.claim_writes: list[tuple] = []


class _ModuleLinter(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, source: str,
                 api_helpers: set[str], graph=None):
        self.path = path
        self.rel = rel
        self.basename = os.path.basename(rel)
        self.lines = source.splitlines()
        self.findings: list[Finding] = []
        self.scope: list[str] = []
        self.held: list[_Held] = []
        self.funcs: list[_FuncState] = []
        # Same-module helper functions returning kube/informer objects
        # (pass 1 of the two-pass taint analysis).
        self.api_helpers = api_helpers
        # Project call graph (callgraph.CallGraph) for the
        # interprocedural rules; None degrades them to silent.
        self.graph = graph
        self._blocking = graph.blocking_closure() if graph is not None \
            else {}
        self.file_allowed: set[str] = set()
        # Header pragma only: scanning the whole source would let a
        # string literal anywhere disable a rule file-wide.
        header = "\n".join(self.lines[:_FILE_ALLOW_LINES])
        for m in _FILE_ALLOW_RE.finditer(header):
            self.file_allowed.update(m.group(1).split(","))
        # Local names bound to the DRIVER's CheckpointManager class,
        # and to its defining MODULE (`from ..kubeletplugin import
        # checkpoint` -> checkpoint.CheckpointManager(...)); TPUDRA007
        # scope. orbax's `orbax.checkpoint` never lands in either set.
        self.checkpoint_manager_aliases: set[str] = set()
        self.checkpoint_module_aliases: set[str] = set()
        # Disambiguate same-shaped findings in one function: fingerprint
        # keys get a #N suffix per repeated (qualname, rule, key).
        self._key_seen: dict[tuple[str, str, str], int] = {}

    # -- plumbing -------------------------------------------------------------

    @property
    def qualname(self) -> str:
        return ".".join(self.scope) or "<module>"

    def _allowed(self, line: int, rule: str) -> bool:
        if rule in self.file_allowed or "*" in self.file_allowed:
            return True
        # The allow comment may sit on the finding's line or -- for
        # lines with no room -- on the (comment-only) line above it.
        # finditer, not search: a line carrying several `allow=` rules
        # (e.g. two suppressions with separate reasons) honors each.
        for lineno in (line, line - 1):
            if not 1 <= lineno <= len(self.lines):
                continue
            text = self.lines[lineno - 1]
            if lineno != line and not text.lstrip().startswith("#"):
                continue
            for m in _ALLOW_RE.finditer(text):
                rules = m.group(1).split(",")
                if "*" in rules or rule in rules:
                    return True
        return False

    def _emit(self, rule: str, node: ast.AST, message: str,
              key: str, edge: str | None = None) -> None:
        line = getattr(node, "lineno", 1)
        if self._allowed(line, rule):
            return
        # A second same-shaped finding in the same function gets a
        # distinct fingerprint (key#2, key#3, ...): one baseline entry
        # must never blanket-suppress future occurrences.
        seen_key = (self.qualname, rule, key)
        n = self._key_seen.get(seen_key, 0) + 1
        self._key_seen[seen_key] = n
        if n > 1:
            key = f"{key}#{n}"
        self.findings.append(Finding(
            rule=rule, path=self.rel, line=line,
            col=getattr(node, "col_offset", 0),
            qualname=self.qualname, message=message, key=key,
            edge=edge,
        ))

    # -- scope handling -------------------------------------------------------

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        module = node.module or ""
        if module == "checkpoint" or module.endswith(".checkpoint"):
            for alias in node.names:
                if alias.name == "CheckpointManager":
                    self.checkpoint_manager_aliases.add(
                        alias.asname or alias.name)
        # `from ..kubeletplugin import checkpoint` (or `from . import
        # checkpoint` inside kubeletplugin/) binds the MODULE.
        if module.endswith("kubeletplugin") or (
                node.level and not module
                and "kubeletplugin/" in self.rel.replace(os.sep, "/")):
            for alias in node.names:
                if alias.name == "checkpoint":
                    self.checkpoint_module_aliases.add(
                        alias.asname or alias.name)
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name.endswith("kubeletplugin.checkpoint") and \
                    alias.asname:
                self.checkpoint_module_aliases.add(alias.asname)
        self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    def _visit_func(self, node) -> None:
        self.scope.append(node.name)
        fs = _FuncState(self.qualname)
        fs.api_params = self._api_object_params(node)
        fs.tainted |= fs.api_params
        fs.released_in_finally, fs.exit_in_finally = \
            self._releases_in_finally(node)
        self.funcs.append(fs)
        outer_held = self.held
        self.held = []  # lock regions don't cross function boundaries
        self.generic_visit(node)
        # TPUDRA018, judged at function close (the rv precondition may
        # be built after the write call in source order): a function
        # that couples try_commit with resourceclaims writes must ride
        # a resourceVersion precondition on those writes.
        if fs.commit_scope and not fs.rv_literal:
            for write_node, what in fs.claim_writes:
                self._emit(
                    "TPUDRA018", write_node,
                    f"commit-protocol write {what}(...) to "
                    "resourceclaims without a resourceVersion "
                    "precondition anywhere in "
                    f"{self.qualname}: the 409 arbiter is what stops "
                    "two active-active schedulers from "
                    "double-allocating (see docs/analysis.md "
                    "'Model checking the commit protocol')",
                    key=f"{what}:resourceclaims",
                )
        self.held = outer_held
        self.funcs.pop()
        self.scope.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    @staticmethod
    def _releases_in_finally(func) -> tuple[set[str], bool]:
        """Base expressions ``.release()``d in a finally block, plus a
        wildcard flag for ``__exit__`` calls. Matching the RELEASED
        lock against the ACQUIRED one is what keeps an unrelated
        ``b.release()`` from excusing a leaked ``a.acquire()``."""
        released: set[str] = set()
        exit_seen = False
        for node in ast.walk(func):
            if isinstance(node, ast.Try):
                for stmt in node.finalbody:
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.Call) and isinstance(
                                sub.func, ast.Attribute):
                            if sub.func.attr == "release":
                                released.add(_unparse(sub.func.value))
                            elif sub.func.attr == "__exit__":
                                exit_seen = True
        return released, exit_seen

    @staticmethod
    def _api_object_params(func) -> set[str]:
        """Parameters the function treats as k8s API objects: anything
        it subscripts/.get()s with a metadata/spec/status key."""
        params = {a.arg for a in func.args.args + func.args.kwonlyargs
                  if a.arg != "self"}
        if not params:
            return set()
        hits: set[str] = set()
        for node in ast.walk(func):
            key = None
            base = None
            if isinstance(node, ast.Subscript) and isinstance(
                    node.slice, ast.Constant):
                key, base = node.slice.value, node.value
            elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and node.func.attr == "get" \
                    and node.args and isinstance(node.args[0], ast.Constant):
                key, base = node.args[0].value, node.func.value
            if key in _META_KEYS and isinstance(base, ast.Name) and \
                    base.id in params:
                hits.add(base.id)
        return hits

    # -- taint helpers (TPUDRA006) -------------------------------------------

    def _fs(self) -> _FuncState | None:
        return self.funcs[-1] if self.funcs else None

    def _is_api_source(self, node: ast.AST) -> bool:
        """Does this expression read from a kube client / informer
        cache / API-object helper?"""
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute):
                chain = _attr_chain(func)
                base = chain[:-1]
                verb = func.attr
                if base and base[-1] == "kube" and verb in ("get", "list"):
                    return True
                if any("informer" in part for part in base) and verb in (
                        "get", "get_by_uid", "list"):
                    return True
                if verb in self.api_helpers and base[:1] == ["self"] \
                        and len(base) == 1:
                    return True
            elif isinstance(func, ast.Name) and func.id in self.api_helpers:
                return True
        return False

    def _is_tainted(self, node: ast.AST) -> bool:
        fs = self._fs()
        if fs is None:
            return False
        if self._is_api_source(node):
            return True
        root = _root_name(node)
        return root is not None and root in fs.tainted

    def _is_copy_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            # {**x} / dict literal / comprehension build new containers
            return isinstance(node, (ast.Dict, ast.DictComp, ast.ListComp,
                                     ast.SetComp, ast.List, ast.Set,
                                     ast.BinOp))
        func = node.func
        name = func.id if isinstance(func, ast.Name) else (
            func.attr if isinstance(func, ast.Attribute) else "")
        if name in _COPY_CALLS:
            return True
        # json.loads(json.dumps(x)) spelled out
        return name == "loads"

    # -- kube client model (TPUDRA008) ----------------------------------------

    @staticmethod
    def _is_kubeclient_ctor(node: ast.AST) -> bool:
        """``KubeClient(...)``, ``kubeclient.KubeClient(...)``, or
        ``KubeClient.from_kubeconfig(...)`` -- the raw-client entry
        points. FakeKubeClient is exempt: the rule polices production
        transport, and the retry wrapper accepts fakes anyway."""
        if not isinstance(node, ast.Call):
            return False
        func = node.func
        if isinstance(func, ast.Name):
            return func.id == "KubeClient"
        if isinstance(func, ast.Attribute):
            if func.attr == "KubeClient":
                return True
            if func.attr == "from_kubeconfig":
                base = func.value
                return (isinstance(base, ast.Name)
                        and base.id == "KubeClient") or (
                            isinstance(base, ast.Attribute)
                            and base.attr == "KubeClient")
        return False

    # -- lock model -----------------------------------------------------------

    def _classify_acquisition(self, expr: ast.AST):
        """(family, level, key) when ``expr`` acquires a lock:
        ``X.acquire(...)`` (flock-like: guard-returning), ``X.hold(...)``
        (sharded chip locks / scheduler node locks), or -- inside the
        scheduler modules -- a bare ``with self._state_lock`` /
        ``with self._alloc_lock`` mutex context."""
        if isinstance(expr, (ast.Attribute, ast.Name)):
            # Plain `with <lock>:` contexts only participate in the
            # scheduler lock model (the prepare pipeline's locks are
            # all acquire()/hold() shaped).
            if self.basename in _SCHED_LOCK_FILES:
                src = _unparse(expr)
                if src.endswith("_state_lock"):
                    return ("sched_state", _LEVEL_SCHED_STATE, src)
                if src.endswith("_alloc_lock"):
                    return ("sched_alloc", _LEVEL_SCHED_ALLOC, src)
            return None
        if not (isinstance(expr, ast.Call)
                and isinstance(expr.func, ast.Attribute)):
            return None
        attr = expr.func.attr
        base = expr.func.value
        base_src = _unparse(base)
        if attr == "hold" and "node_locks" in base_src and \
                self.basename in _SCHED_LOCK_FILES:
            return ("sched_node", _LEVEL_SCHED_NODE, base_src)
        if attr == "hold" and "shard" in base_src:
            return ("shard", _LEVEL_SHARD, base_src)
        if attr == "acquire":
            level = _LEVEL_RESERVATION if base_src.endswith("pu_lock") \
                else None
            return ("flock", level, base_src)
        return None

    def _check_acquisition_order(self, family: str, level: int | None,
                                 key: str, node: ast.AST) -> None:
        held_levels = [h.level for h in self.held if h.level is not None]
        if level is not None and held_levels and level < max(held_levels):
            inner = max(self.held, key=lambda h: h.level or 0)
            order_doc = ("node locks -> _state_lock -> _alloc_lock"
                         if level >= _LEVEL_SCHED_NODE
                         else "reservation -> shard -> checkpoint")
            self._emit(
                "TPUDRA001", node,
                f"acquires level-{level} lock {key!r} while holding "
                f"level-{inner.level} lock {inner.key!r} (line "
                f"{inner.line}); documented order is {order_doc}",
                key=f"{inner.key}>{key}",
            )
        if family == "flock":
            for h in self.held:
                if h.family == "flock" and h.key == key:
                    self._emit(
                        "TPUDRA004", node,
                        f"re-acquires flock {key!r} already held since "
                        f"line {h.line}; Flock is not re-entrant "
                        "(FlockReentrantError at runtime)",
                        key=key,
                    )

    # -- visitors -------------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        entered: list[_Held] = []
        for item in node.items:
            # TPUDRA012: a span opened as a with-item is the sanctioned
            # form; mark it so visit_Call's bare-start_span check
            # skips it.
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                fname = (expr.func.id if isinstance(expr.func, ast.Name)
                         else expr.func.attr
                         if isinstance(expr.func, ast.Attribute) else "")
                if fname in ("span", "start_span"):
                    expr._tpudra_with = True  # type: ignore[attr-defined]
            acq = self._classify_acquisition(item.context_expr)
            if acq is not None:
                family, level, key = acq
                self._check_acquisition_order(family, level, key,
                                              item.context_expr)
                held = _Held(family, level, key, node.lineno)
                self.held.append(held)
                entered.append(held)
                # Mark the with-item call visited so visit_Call's bare-
                # acquire check skips it.
                item.context_expr._tpudra_with = True  # type: ignore[attr-defined]
        self.generic_visit(node)
        for _ in entered:
            self.held.pop()

    visit_AsyncWith = visit_With

    def _partition_spec_sanctioned(self) -> bool:
        """TPUDRA014 scope check: inside pkg/autoscale/ or one of the
        sanctioned rel-path suffixes."""
        rel_posix = self.rel.replace(os.sep, "/")
        return (any(rel_posix.endswith(sfx)
                    for sfx in _PARTITION_SPEC_SUFFIXES)
                or any(d in rel_posix for d in _PARTITION_SPEC_DIRS))

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func

        # TPUDRA008 plumbing: a RetryingKubeClient(...) call sanctions
        # every KubeClient construction anywhere inside its arguments
        # (incl. `Fake() if standalone else KubeClient()` conditionals).
        wrapper_name = func.id if isinstance(func, ast.Name) else (
            func.attr if isinstance(func, ast.Attribute) else "")
        if wrapper_name == "RetryingKubeClient":
            for sub in ast.walk(node):
                if sub is not node and self._is_kubeclient_ctor(sub):
                    sub._tpudra_wrapped = True  # type: ignore[attr-defined]

        # TPUDRA012: bare Span / FlightEvent construction outside the
        # tracing layer, and start_span() outside a with statement.
        # The public APIs (tracing.span context manager,
        # FlightRecorder.record) are the only sanctioned producers --
        # an unfinished span is never exported and mis-parents every
        # later span on its thread; a hand-built FlightEvent bypasses
        # the ring's locking and capacity.
        if wrapper_name == "Span" and \
                self.basename not in _SPAN_CTOR_FILES:
            self._emit(
                "TPUDRA012", node,
                "bare Span(...) construction outside pkg/tracing.py; "
                "use the with-guarded tracing.span(...) API",
                key="Span",
            )
        if wrapper_name == "FlightEvent" and \
                self.basename not in _FLIGHT_EVENT_FILES:
            self._emit(
                "TPUDRA012", node,
                "bare FlightEvent(...) construction outside "
                "pkg/flightrecorder.py; use FlightRecorder.record(...)",
                key="FlightEvent",
            )
        if wrapper_name == "start_span" and \
                not getattr(node, "_tpudra_with", False) and \
                self.basename not in _START_SPAN_FILES:
            self._emit(
                "TPUDRA012", node,
                "start_span(...) outside a with statement: the span is "
                "never finished/exported on the error path; use "
                "`with tracing.span(...)` (SegmentTimer is the "
                "sanctioned non-lexical holder)",
                key="start_span",
            )
        # The public span() helper held outside `with` is the identical
        # leak under the other spelling (span() just returns
        # start_span()'s result). Matched as bare `span(` or
        # `tracing.span(` so a same-named helper on some OTHER object
        # never trips it.
        if wrapper_name == "span" and \
                (isinstance(func, ast.Name)
                 or (isinstance(func, ast.Attribute)
                     and isinstance(func.value, ast.Name)
                     and func.value.id == "tracing")) and \
                not getattr(node, "_tpudra_with", False) and \
                self.basename not in _START_SPAN_FILES:
            self._emit(
                "TPUDRA012", node,
                "tracing.span(...) outside a with statement: the span "
                "is never finished/exported on the error path; use it "
                "as the context expression of `with`",
                key="span",
            )

        # TPUDRA014: PartitionSet spec/profile construction outside
        # the autoscale control plane / spec definition site. The
        # classmethod readers (from_dict/from_file) stay open -- they
        # PARSE an authored layout; only direct construction AUTHORS
        # one.
        if wrapper_name in ("PartitionSet", "PartitionProfile") and \
                not self._partition_spec_sanctioned():
            self._emit(
                "TPUDRA014", node,
                f"{wrapper_name}(...) constructed outside "
                "pkg/autoscale/ / pkg/partition/spec.py: desired "
                "partition layouts are authored by the autoscale "
                "planner (or parsed via PartitionSet.from_dict/"
                "from_file), never built ad hoc",
                key=wrapper_name,
            )

        # TPUDRA008: raw KubeClient construction outside the wrapper.
        if self._is_kubeclient_ctor(node) and \
                not getattr(node, "_tpudra_wrapped", False) and \
                self.basename not in _RAW_KUBECLIENT_FILES:
            self._emit(
                "TPUDRA008", node,
                "raw KubeClient constructed outside RetryingKubeClient: "
                "no backoff/deadline/circuit-breaker discipline "
                "(pkg/retry.py)",
                key="KubeClient",
            )

        if isinstance(func, ast.Attribute):
            attr = func.attr
            base_src = _unparse(func.value)

            # TPUDRA013: telemetry ring / fleet-aggregator mutation
            # outside the telemetry layer. The mutating surface is the
            # distinctively-named record_sample / fold_* methods
            # (pkg/fleetstate.py); everyone else uses the read surface
            # or FleetAggregator.observe_pass.
            if (attr == "record_sample" or attr.startswith("fold_")) \
                    and not any(
                        self.rel.replace(os.sep, "/").endswith(sfx)
                        for sfx in _TELEMETRY_MUT_SUFFIXES):
                self._emit(
                    "TPUDRA013", node,
                    f"telemetry state mutation {base_src}.{attr}(...) "
                    "outside pkg/fleetstate.py / pkg/anomaly.py / "
                    "kubeletplugin/health.py: feed samples through the "
                    "health-poll seam (ChipHealthMonitor) or fold "
                    "through FleetAggregator.observe_pass",
                    key=f"{base_src}.{attr}",
                )

            # TPUDRA015: power-ledger / pre-warm warm-set mutation
            # outside the definition sites. The mutating surface is
            # the distinctively-named power_debit/power_credit
            # (pkg/schedcache.AllocationState) and set_prewarm
            # (pkg/partition/engine.PartitionEngine).
            rel_posix = self.rel.replace(os.sep, "/")
            if attr in ("power_debit", "power_credit") and not any(
                    rel_posix.endswith(sfx)
                    for sfx in _POWER_MUT_SUFFIXES):
                self._emit(
                    "TPUDRA015", node,
                    f"power-ledger mutation {base_src}.{attr}(...) "
                    "outside pkg/schedcache.py: the per-node power "
                    "budget is balanced only by AllocationState's own "
                    "apply/release/retarget paths (try_commit judges "
                    "it atomically); read power_snapshot() instead",
                    key=f"{base_src}.{attr}",
                )
            if attr == "set_prewarm" and not any(
                    rel_posix.endswith(sfx)
                    for sfx in _PREWARM_MUT_SUFFIXES):
                self._emit(
                    "TPUDRA015", node,
                    f"pre-warm mutation {base_src}.{attr}(...) outside "
                    "pkg/partition/engine.py / kubeletplugin/"
                    "driver.py: the warm carve-out set converges from "
                    "the PartitionSet CRD's prewarm annotation "
                    "(Driver.apply_prewarm), never ad hoc",
                    key=f"{base_src}.{attr}",
                )

            # TPUDRA014 (write half): apiserver writes to the
            # partitionsets CRD outside the autoscale control plane.
            # Any kube write verb with a "partitionsets" literal
            # resource argument is an authoring site.
            if attr in _PARTITION_CRD_WRITE_VERBS and any(
                    isinstance(a, ast.Constant)
                    and a.value == "partitionsets"
                    for a in node.args) and \
                    not self._partition_spec_sanctioned():
                self._emit(
                    "TPUDRA014", node,
                    f"partitionsets CRD write {base_src}.{attr}(...) "
                    "outside pkg/autoscale/: re-plans roll out "
                    "through the AutoscaleController's durable "
                    "records, never ad hoc",
                    key=f"{base_src}.{attr}:partitionsets",
                )

            # TPUDRA011: carve-out registry mutation outside the
            # partition engine / DeviceState. The registry attribute is
            # deliberately named *_registry in both sanctioned modules,
            # so the textual match covers `self._registry`,
            # `state.subslice_registry`, and module-level bindings.
            if attr in ("create", "destroy") and \
                    base_src.endswith("_registry"):
                rel_posix = self.rel.replace(os.sep, "/")
                sanctioned = (
                    self.basename in _CARVEOUT_FILES
                    or any(rel_posix.endswith(sfx)
                           for sfx in _CARVEOUT_REL_SUFFIXES)
                )
                if not sanctioned:
                    self._emit(
                        "TPUDRA011", node,
                        f"carve-out registry mutation {base_src}."
                        f"{attr}(...) outside the partition engine / "
                        "DeviceState: route through "
                        "PartitionEngine.attach/detach or the claim "
                        "prepare pipeline",
                        key=f"{base_src}.{attr}",
                    )

            # TPUDRA009 (sub-snapshot fence): mutator method on a
            # protected schedcache internal (snap.candidates.append,
            # pool.sel_cache.update, snap.pools.pop, ...) outside the
            # sanctioned delta paths.
            if attr in _MUTATORS and isinstance(func, ast.Attribute):
                self._check_snapshot_internal_write(
                    func.value, node, f"{attr}()")

            # TPUDRA009: raw kube.list of a watched resource inside the
            # scheduler's sync paths -- these reads must come from the
            # informer-backed ClusterView / inventory snapshot.
            if attr == "list" and self.basename in _SCHED_SYNC_FILES:
                chain = _attr_chain(func)
                listed = {
                    a.value for a in node.args
                    if isinstance(a, ast.Constant)
                    and isinstance(a.value, str)
                }
                watched = sorted(listed & _WATCHED_RESOURCES)
                if watched and chain[:-1] and "kube" in chain[-2]:
                    self._emit(
                        "TPUDRA009", node,
                        f"scheduler sync path lists watched resource"
                        f"(s) {', '.join(watched)} via {base_src}.list; "
                        "read through the ClusterView/snapshot "
                        "(pkg/schedcache) instead",
                        key=f"{base_src}.list:{','.join(watched)}",
                    )

            # TPUDRA002: acquire outside a with-guard. The release in
            # the finally must be of the SAME lock expression (or an
            # __exit__ wildcard) -- an unrelated b.release() must not
            # excuse a leaked a.acquire().
            if attr == "acquire" and not getattr(node, "_tpudra_with",
                                                 False):
                fs = self._fs()
                if fs is None or not (
                        fs.exit_in_finally
                        or base_src in fs.released_in_finally):
                    self._emit(
                        "TPUDRA002", node,
                        f"{base_src}.acquire(...) without a with-guard "
                        f"or a {base_src}.release() in a finally block "
                        f"in {self.qualname}",
                        key=base_src,
                    )

            # Out-of-with acquisitions still participate in ordering /
            # re-entrancy checks (e.g. bare pu_lock.acquire in a shard
            # region).
            if attr in ("acquire", "hold") and not getattr(
                    node, "_tpudra_with", False):
                acq = self._classify_acquisition(node)
                if acq is not None:
                    self._check_acquisition_order(*acq, node)

            # Checkpoint-manager calls are level-3 acquisitions for the
            # ordering model (they take the checkpoint flock inside).
            if attr in _CHECKPOINT_CALLS and base_src.endswith("_checkpoint"):
                self._check_acquisition_order(
                    "checkpoint", _LEVEL_CHECKPOINT, base_src, node)

            # TPUDRA003: blocking calls under shard lock / flock.
            if any(h.family in ("flock", "shard") for h in self.held):
                blocking = None
                chain = _attr_chain(func)
                if chain == ["time", "sleep"]:
                    blocking = "time.sleep"
                elif chain[:1] == ["subprocess"] and attr in (
                        "run", "call", "check_call", "check_output"):
                    blocking = f"subprocess.{attr}"
                elif attr == "wait" and chain[:1] != ["self"] and \
                        "event" not in base_src.lower() and \
                        base_src.endswith("proc"):
                    blocking = f"{base_src}.wait"
                elif attr in _KUBE_VERBS and chain[:-1] and \
                        chain[-2] == "kube":
                    blocking = f"{base_src}.{attr}"
                if blocking is not None:
                    holder = next(h for h in self.held
                                  if h.family in ("flock", "shard"))
                    self._emit(
                        "TPUDRA003", node,
                        f"blocking call {blocking}(...) while holding "
                        f"{holder.family} lock {holder.key!r} (held "
                        f"since line {holder.line})",
                        key=f"{holder.key}:{blocking}",
                    )

            # TPUDRA010: kube I/O under the scheduler registry /
            # allocation-state locks. These must stay brief bookkeeping
            # sections so disjoint allocations commit in parallel --
            # commit I/O belongs under the per-node locks (which are
            # deliberately NOT in this check's scope).
            if any(h.family in _SCHED_LOCK_FAMILIES for h in self.held):
                chain = _attr_chain(func)
                is_kube = (attr in _KUBE_VERBS and chain[:-1]
                           and chain[-2] == "kube")
                is_sleep = chain == ["time", "sleep"]
                if is_kube or is_sleep:
                    holder = next(h for h in self.held
                                  if h.family in _SCHED_LOCK_FAMILIES)
                    what = f"{base_src}.{attr}" if is_kube else \
                        "time.sleep"
                    self._emit(
                        "TPUDRA010", node,
                        f"blocking call {what}(...) while holding "
                        f"scheduler lock {holder.key!r} (held since "
                        f"line {holder.line}); move the I/O outside or "
                        "under the per-node locks",
                        key=f"{holder.key}:{what}",
                    )

            # TPUDRA018 raw material: does this function couple a
            # try_commit reservation with resourceclaims writes?
            fs = self._fs()
            if fs is not None:
                if attr == "try_commit":
                    fs.commit_scope = True
                chain = _attr_chain(func)
                if attr in ("patch", "update") and chain[:-1] and \
                        chain[-2] == "kube" and any(
                            isinstance(a, ast.Constant)
                            and a.value == "resourceclaims"
                            for a in node.args):
                    fs.claim_writes.append(
                        (node, f"{base_src}.{attr}"))

            # TPUDRA008 (second half): a kube verb on a raw (unwrapped)
            # KubeClient local without an explicit timeout parks the
            # calling thread on the urllib default when the apiserver
            # wedges -- the retry wrapper injects one per attempt.
            fs = self._fs()
            if fs is not None and attr in _KUBE_VERBS and \
                    isinstance(func.value, ast.Name) and \
                    func.value.id in fs.raw_kube and \
                    not any(kw.arg == "timeout" for kw in node.keywords):
                self._emit(
                    "TPUDRA008", node,
                    f"kube {attr}() on raw client {func.value.id!r} "
                    "without an explicit timeout=",
                    key=f"{func.value.id}.{attr}:timeout",
                )

            # TPUDRA006: mutator method on a tainted object.
            if attr in _MUTATORS and self._is_tainted(func.value):
                self._emit(
                    "TPUDRA006", node,
                    f"in-place .{attr}() on cached API object "
                    f"{_unparse(func.value)!r}; deep-copy before "
                    "mutating (client-go informer rule)",
                    key=f"{_root_name(func.value)}.{attr}",
                )

        # -- interprocedural rules (call-graph resolved) ----------------------
        spelling = None
        if isinstance(func, ast.Name):
            spelling = func.id
        elif isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Name):
            spelling = f"{func.value.id}.{func.attr}"
        caller = self._graph_caller() if spelling is not None else None
        if caller is not None:
            # TPUDRA017: a call that is not ITSELF a blocking sink
            # (those are TPUDRA003/010) but transitively reaches kube
            # I/O or sleep through the call graph, made while a
            # hierarchy lock is held. Per-node commit locks are
            # sanctioned for commit I/O (same carve-out as TPUDRA010).
            holder = next(
                (h for h in self.held
                 if h.family in ("flock", "shard") + _SCHED_LOCK_FAMILIES),
                None)
            if holder is not None and not self._is_direct_sink(func):
                for callee_qn in self.graph.resolve(caller, spelling):
                    hit = self._blocking.get(callee_qn)
                    if hit is None:
                        continue
                    kind, label, line, path = hit
                    from .callgraph import render_edge
                    edge = render_edge(
                        [caller.qualname] + path, label, line)
                    self._emit(
                        "TPUDRA017", node,
                        f"{spelling}(...) transitively performs "
                        f"{'kube I/O' if kind == 'kube' else label}"
                        f" while holding {holder.family} lock "
                        f"{holder.key!r} (held since line "
                        f"{holder.line}); witness: {edge}",
                        key=f"{holder.key}:{spelling}",
                        edge=edge,
                    )
                    break
            # TPUDRA016: a tainted (informer-cached / API) object
            # handed to a CROSS-MODULE helper that writes through the
            # parameter -- mutation laundered past the intra-module
            # taint pass.
            if self.graph is not None:
                self._check_laundered_mutation(node, caller, spelling)

        # TPUDRA007: CheckpointManager(...) without transition_policy.
        # In scope: the class imported from the driver's checkpoint
        # module, by name or through a module alias -- an
        # `ocp.CheckpointManager(...)` (orbax) or any other same-named
        # class must not trip the rule.
        is_driver_cm = (
            isinstance(func, ast.Name)
            and func.id in self.checkpoint_manager_aliases
        ) or (
            isinstance(func, ast.Attribute)
            and func.attr == "CheckpointManager"
            and isinstance(func.value, ast.Name)
            and func.value.id in self.checkpoint_module_aliases
        )
        if is_driver_cm:
            if not any(kw.arg == "transition_policy"
                       for kw in node.keywords):
                self._emit(
                    "TPUDRA007", node,
                    "CheckpointManager constructed without "
                    "transition_policy=: the mutation site opts out of "
                    "the checkpoint state-machine validator",
                    key="CheckpointManager",
                )

        self.generic_visit(node)

    # -- interprocedural helpers ----------------------------------------------

    def _graph_caller(self):
        """The call-graph FunctionNode for the CURRENT lexical scope
        (graph nodes exist for top-level functions and Class.method;
        nested defs resolve to their enclosing function)."""
        if self.graph is None or not self.scope:
            return None
        if len(self.scope) >= 2:
            qn = self.graph.module_classes.get(self.rel, {}).get(
                self.scope[0], {}).get(self.scope[1])
            if qn is not None:
                return self.graph.nodes.get(qn)
        qn = self.graph.module_funcs.get(self.rel, {}).get(
            self.scope[0])
        return self.graph.nodes.get(qn) if qn is not None else None

    @staticmethod
    def _is_direct_sink(func: ast.AST) -> bool:
        """Is this call itself the blocking sink TPUDRA003/010 already
        police (kube verb / time.sleep)?"""
        if not isinstance(func, ast.Attribute):
            return False
        chain = _attr_chain(func)
        if chain == ["time", "sleep"]:
            return True
        return func.attr in _KUBE_VERBS and len(chain) >= 2 and \
            chain[-2] == "kube"

    def _check_laundered_mutation(self, node: ast.Call, caller,
                                  spelling: str) -> None:
        """TPUDRA016: tainted API object passed to a cross-module
        helper that mutates the matching parameter in place."""
        from .callgraph import render_edge
        callees = self.graph.mutating_callees(caller, spelling)
        if not callees:
            return
        args = [(i, a) for i, a in enumerate(node.args)]
        for callee in callees:
            if callee.rel == self.rel:
                continue  # same module: the local taint pass's beat
            for i, arg in args:
                if i >= len(callee.params):
                    break
                param = callee.params[i]
                if param not in callee.mutates_params:
                    continue
                if not self._is_tainted(arg) or self._is_copy_call(arg):
                    continue
                edge = render_edge(
                    [caller.qualname, callee.qualname],
                    f"mutates {param!r}", callee.lineno)
                self._emit(
                    "TPUDRA016", node,
                    f"cached API object {_unparse(arg)!r} passed to "
                    f"cross-module helper {spelling}(...) which "
                    f"mutates its {param!r} parameter in place "
                    f"({callee.rel}:{callee.lineno}); deep-copy "
                    f"before the call; witness: {edge}",
                    key=f"{spelling}:{param}",
                    edge=edge,
                )
                return

    def _snapshot_mut_sanctioned(self) -> bool:
        rel_posix = self.rel.replace(os.sep, "/")
        return any(rel_posix.endswith(sfx)
                   for sfx in _SNAPSHOT_MUT_SUFFIXES)

    def _check_snapshot_internal_write(self, container,
                                       node, how: str) -> None:
        """TPUDRA009 (sub-snapshot fence): ``container`` is the
        expression whose contents are being mutated (e.g. the
        ``snap.order_cache`` in ``snap.order_cache[k] = v``); flag it
        when it is a protected schedcache internal and this module is
        not sanctioned."""
        if not isinstance(container, ast.Attribute):
            return
        if container.attr not in _SNAPSHOT_INTERNAL_ATTRS:
            return
        # A class initializing ITS OWN attribute of the same name is
        # someone else's business (self.X = ... / self.X.append(...)).
        root = container.value
        if isinstance(root, ast.Name) and root.id == "self":
            return
        if self._snapshot_mut_sanctioned():
            return
        src = _unparse(container)
        self._emit(
            "TPUDRA009", node,
            f"{how} of per-pool sub-snapshot internal {src!r} outside "
            "pkg/schedcache.py: these structures are shared by "
            "identity across snapshot generations -- mutate only "
            "through schedcache delta paths (topology order memos: "
            "order_memo_get/put)",
            key=f"snapmut:{src}:{how}",
        )

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            if isinstance(target, ast.Subscript):
                self._check_snapshot_internal_write(
                    target.value, node, "subscript write")
            elif isinstance(target, ast.Attribute):
                self._check_snapshot_internal_write(
                    target, node, "attribute rebind")
        fs = self._fs()
        if fs is not None:
            # TPUDRA008 bookkeeping: locals bound to a raw KubeClient.
            raw_ctor = self._is_kubeclient_ctor(node.value) and \
                not getattr(node.value, "_tpudra_wrapped", False)
            for target in node.targets:
                if isinstance(target, ast.Name):
                    if raw_ctor:
                        fs.raw_kube.add(target.id)
                    else:
                        fs.raw_kube.discard(target.id)
            value_tainted = self._is_tainted(node.value) and \
                not self._is_copy_call(node.value)
            for target in node.targets:
                if isinstance(target, ast.Name):
                    if value_tainted:
                        fs.tainted.add(target.id)
                    else:
                        fs.tainted.discard(target.id)
                elif isinstance(target, (ast.Subscript, ast.Attribute)):
                    # TPUDRA006: writing into a tainted object.
                    if self._is_tainted(target.value):
                        self._emit(
                            "TPUDRA006", node,
                            "in-place assignment into cached API object "
                            f"{_unparse(target.value)!r}; deep-copy "
                            "before mutating",
                            key=f"{_root_name(target.value)}[]=",
                        )
                elif isinstance(target, (ast.Tuple, ast.List)):
                    for elt in target.elts:
                        if isinstance(elt, ast.Name):
                            if value_tainted:
                                fs.tainted.add(elt.id)
                            else:
                                fs.tainted.discard(elt.id)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        target = node.target
        if isinstance(target, ast.Subscript):
            self._check_snapshot_internal_write(
                target.value, node, "augmented subscript write")
        elif isinstance(target, ast.Attribute):
            # snap.order_cache |= {...} / pool.candidates += [...]
            # mutate the shared internal just as surely as a
            # subscript write.
            self._check_snapshot_internal_write(
                target, node, "augmented attribute write")
        if isinstance(target, (ast.Subscript, ast.Attribute)) and \
                self._is_tainted(target.value):
            self._emit(
                "TPUDRA006", node,
                "augmented assignment into cached API object "
                f"{_unparse(target.value)!r}; deep-copy before mutating",
                key=f"{_root_name(target.value)}aug=",
            )
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            if isinstance(target, ast.Subscript):
                self._check_snapshot_internal_write(
                    target.value, node, "del")
        for target in node.targets:
            if isinstance(target, (ast.Subscript, ast.Attribute)) and \
                    self._is_tainted(target.value):
                self._emit(
                    "TPUDRA006", node,
                    "del on cached API object "
                    f"{_unparse(target.value)!r}; deep-copy before "
                    "mutating",
                    key=f"del {_root_name(target.value)}",
                )
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        fs = self._fs()
        if fs is not None and self._is_tainted(node.iter):
            for elt in ast.walk(node.target):
                if isinstance(elt, ast.Name):
                    fs.tainted.add(elt.id)
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant) -> None:
        if node.value == "resourceVersion":
            fs = self._fs()
            if fs is not None:
                fs.rv_literal = True
        if isinstance(node.value, str) and node.value in _STATE_LITERALS \
                and self.basename not in _STATE_LITERAL_FILES:
            self._emit(
                "TPUDRA005", node,
                f"raw claim-state literal {node.value!r}; use "
                "ClaimState (kubeletplugin/checkpoint.py) or the "
                "statemachine model constants",
                key=node.value,
            )
        self.generic_visit(node)


def _collect_api_helpers(tree: ast.Module) -> set[str]:
    """Pass 1: names of module functions/methods that return kube- or
    informer-derived objects (one level deep)."""
    helpers: set[str] = set()

    def returns_api(func) -> bool:
        sources: set[str] = set()
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) and _looks_api(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        sources.add(t.id)
            if isinstance(node, ast.For) and _looks_api(node.iter):
                for elt in ast.walk(node.target):
                    if isinstance(elt, ast.Name):
                        sources.add(elt.id)
        for node in ast.walk(func):
            if isinstance(node, ast.Return) and node.value is not None:
                if _looks_api(node.value):
                    return True
                root = _root_name(node.value)
                if root is not None and root in sources:
                    return True
        return False

    def _looks_api(node: ast.AST) -> bool:
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            chain = _attr_chain(node.func)
            if len(chain) >= 2 and chain[-2] == "kube" and \
                    chain[-1] in ("get", "list"):
                return True
            if any("informer" in p for p in chain[:-1]) and chain[-1] in (
                    "get", "get_by_uid", "list"):
                return True
        return False

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if returns_api(node):
                helpers.add(node.name)
    return helpers


def lint_source(source: str, rel: str = "<string>",
                path: str = "<string>", graph=None) -> list[Finding]:
    """Lint one module's source; returns its findings (unbaselined).

    ``graph`` is the project CallGraph for the interprocedural rules;
    when omitted a single-module graph is built from this source, so
    TPUDRA017 still sees same-module helper chains (TPUDRA016 is
    cross-module by definition and stays silent)."""
    tree = ast.parse(source, filename=rel)
    if graph is None:
        from .callgraph import CallGraph
        graph = CallGraph.build({rel: source})
    linter = _ModuleLinter(path, rel, source,
                           api_helpers=_collect_api_helpers(tree),
                           graph=graph)
    linter.visit(tree)
    return linter.findings


def iter_python_files(paths: list[str]) -> list[str]:
    out: list[str] = []
    for path in paths:
        if os.path.isfile(path):
            out.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git", "native")]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    out.append(os.path.join(dirpath, name))
    return sorted(out)


class Baseline:
    """The committed suppression file: fingerprint -> reason."""

    def __init__(self, suppressions: dict[str, str] | None = None,
                 path: str | None = None):
        self.suppressions = dict(suppressions or {})
        self.path = path

    @classmethod
    def load(cls, path: str | None) -> "Baseline":
        if not path or not os.path.exists(path):
            return cls(path=path)
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        return cls(doc.get("suppressions", {}), path=path)

    def save(self, path: str | None = None) -> None:
        target = path or self.path
        if not target:
            raise ValueError("baseline has no path")
        doc = {"version": 1, "suppressions": dict(sorted(
            self.suppressions.items()))}
        with open(target, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")

    def apply(self, findings: list[Finding]) -> None:
        for f in findings:
            if f.fingerprint in self.suppressions:
                f.baselined = True


def run_lint(paths: list[str], baseline: Baseline | str | None = None,
             root: str | None = None) -> LintReport:
    """Lint every .py under ``paths``. ``root`` anchors the relative
    paths used in fingerprints (defaults to the common prefix's dir)."""
    if isinstance(baseline, str):
        baseline = Baseline.load(baseline)
    files = iter_python_files(paths)
    if root is None:
        root = os.path.commonpath([os.path.abspath(p) for p in paths]) \
            if paths else os.getcwd()
        if os.path.isfile(root):
            root = os.path.dirname(root)
    report = LintReport()
    sources: dict[str, tuple[str, str]] = {}  # rel -> (path, source)
    for path in files:
        rel = os.path.relpath(os.path.abspath(path), root)
        # Fingerprints must be stable across checkouts.
        rel = rel.replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                sources[rel] = (path, f.read())
        except OSError:
            continue
    # One project-wide call graph so the interprocedural rules
    # (TPUDRA016/017) resolve edges across every linted module.
    from .callgraph import CallGraph
    graph = CallGraph.build({rel: src for rel, (_, src)
                             in sources.items()})
    for rel, (path, source) in sources.items():
        try:
            report.findings.extend(
                lint_source(source, rel=rel, path=path, graph=graph))
        except SyntaxError as e:
            report.findings.append(Finding(
                rule="TPUDRA000", path=rel, line=e.lineno or 1, col=0,
                qualname="<module>", message=f"syntax error: {e.msg}",
                key="syntax",
            ))
        report.files_scanned += 1
    if baseline is not None:
        baseline.apply(report.findings)
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return report


def metrics_exposition(report: LintReport) -> str:
    """Prometheus text exposition of the finding counts
    (``tpu_dra_lint_findings_total`` by rule ID) for BASELINE.md /
    dashboard ingestion from bench or CI runs."""
    lines = [
        "# HELP tpu_dra_lint_findings_total Non-baselined static-"
        "analysis findings by rule ID.",
        "# TYPE tpu_dra_lint_findings_total gauge",
    ]
    for rule, n in sorted(report.counts().items()):
        lines.append(f'tpu_dra_lint_findings_total{{rule="{rule}"}} {n}')
    lines += [
        "# HELP tpu_dra_lint_baselined_total Baseline-suppressed "
        "findings by rule ID.",
        "# TYPE tpu_dra_lint_baselined_total gauge",
    ]
    counts_base: dict[str, int] = {rule: 0 for rule in RULES}
    for f in report.baselined:
        counts_base[f.rule] = counts_base.get(f.rule, 0) + 1
    for rule, n in sorted(counts_base.items()):
        lines.append(
            f'tpu_dra_lint_baselined_total{{rule="{rule}"}} {n}')
    return "\n".join(lines) + "\n"
