"""Multi-actor protocol model checker for the commit/prepare/recovery
protocols -- the whole-driver companion to ``interleave``'s per-node
pipeline explorer.

Where ``interleave`` permutes thread schedules inside ONE process,
this module models the DISTRIBUTED protocol: several actors (two
active-active schedulers sharing a placement domain, a node plugin, a
recovery controller) run as workers under the same
``ControlledScheduler``, but every Kubernetes verb goes through one
modeled apiserver (:class:`ModelApiServer`) with real resourceVersion
semantics -- stale informer reads, 409s on preconditioned writes,
watch-event delay, and crash-restart of any actor all become explicit
``choice()`` points the DFS enumerates.

The protocol under test is the driver's own commit-then-observe shape
(``scheduler._commit_allocation`` + lint rule TPUDRA018): a fit is
planned against a possibly-stale informer cache, the reservation write
rides the resourceVersion that plan READ, and the apiserver's 409 is
the only cross-process arbiter. ``--seeded-bug`` (and the first leg of
``--smoke``) removes exactly that precondition -- the write becomes a
blind merge-patch -- and the checker must find, minimize, and
deterministically replay a double-allocation; with the precondition
intact, the same scenario must survive every explored schedule.

Machine-checked invariants (evaluated on the quiesced end state, plus
inline during execution where noted):

- **No double-allocation**: no device key appears in two claims'
  ``status.allocation`` (extracted with the real
  ``AllocationState._alloc_keys``), and the domain ledger maps each
  device to at most one claim.
- **Ledger/status convergence**: every stamped claim is backed by the
  matching ledger entry and vice versa -- the two views of truth agree
  once all actors quiesce.
- **Power ledger never over-commits**: per-node sum of the rated watts
  of status-referenced devices stays within the node cap (double
  allocation of a chip is also a double power debit).
- **Every claim converges**: all claims end allocated and stamped
  (liveness via each actor's deterministic drain phase).
- **TransitionPolicies hold across crashes**: every durable checkpoint
  write is validated inline against its ``TransitionPolicy``
  (TWO_PHASE for the node plugin, EVICTION for the recovery
  controller, MIGRATION for the cooperative-move controller),
  including writes on the post-crash resume path.
- **Migration never leaks**: the checkpoint-then-switch handshake
  (:class:`MigrationScenario`) ends every explored schedule -- stale
  plan reads, delayed/never acks, crash-restart at each seam, a racing
  claim delete -- with no reservation marker left in the ledger, no
  undrained move record, and the undeleted claim allocated on source
  or destination (the cold fallback never strands it).

Exploration is DFS (``interleave.explore``) plus seeded-random
sampling, with a conservative partial-order reduction
(:func:`independent_ops`) and failure-schedule minimization
(:func:`minimize_failure`) producing deterministic replay artifacts
(``--json-out`` / ``--replay``).

Run: ``python -m k8s_dra_driver_gpu_tpu.pkg.analysis.modelcheck
--smoke`` (CI, seconds) or ``--full`` (pre-release, >= 10k schedules).
Dev tooling: imported explicitly, never via the package ``__init__``.
"""

from __future__ import annotations

import argparse
import copy
import json
import sys

from ..kubeclient import ConflictError, NotFoundError
from ..schedcache import AllocationState, claim_like
from .interleave import (
    ControlledScheduler,
    ExplorationResult,
    ReplayChooser,
    _run_one,
    explore,
    explore_random,
)
from .statemachine import (
    EVICTION_DEALLOCATED,
    EVICTION_DRAINING,
    EVICTION_PLANNED,
    EVICTION_POLICY,
    MIGRATION_DEST_RESERVED,
    MIGRATION_INTENT_SIGNALED,
    MIGRATION_POLICY,
    MIGRATION_SWITCHING,
    MIGRATION_WORKLOAD_ACKED,
    PREPARE_COMPLETED,
    PREPARE_STARTED,
    TWO_PHASE_POLICY,
    crash_closure_all,
)

DRIVER = "tpu.example.com"
POOL = "pool-a"


class _ActorCrash(BaseException):
    """Unwinds an actor at a modeled fault seam. BaseException so actor
    code's ``except Exception`` retry handling cannot swallow a modeled
    crash -- only the actor wrapper's restart loop catches it."""


class ModelApiServer:
    """One modeled apiserver: named objects, a global resourceVersion
    counter, and REAL optimistic-concurrency semantics.

    - ``update`` replaces an object; a resourceVersion in the incoming
      metadata is a precondition (mismatch raises ConflictError -- the
      same class the real and fake clients raise).
    - ``patch`` is JSON merge-patch; a resourceVersion in the patch
      body is likewise a precondition (matching FakeKubeClient.patch
      and the real apiserver), and a PATCH WITHOUT one is the
      last-write-wins blind merge the seeded bug exploits.
    - Every successful write appends a full deep copy to each
      subscriber queue (the modeled watch stream) and to ``history``
      (for invariants over intermediate states).

    Not thread-safe on purpose: exactly one worker runs at a time under
    the ControlledScheduler, so locks here would only hide missing
    yield points.
    """

    def __init__(self, objects: dict[str, dict] | None = None):
        self._rv = 0
        self._store: dict[str, dict] = {}
        self._queues: dict[str, list[tuple[str, dict]]] = {}
        self.history: list[tuple[str, dict]] = []
        for name, obj in (objects or {}).items():
            self._install(name, copy.deepcopy(obj))

    def _install(self, name: str, obj: dict) -> None:
        self._rv += 1
        md = dict(obj.get("metadata") or {})
        md["resourceVersion"] = str(self._rv)
        md.setdefault("name", name)
        self._store[name] = {**obj, "metadata": md}

    def _broadcast(self, name: str) -> None:
        snap = copy.deepcopy(self._store[name])
        self.history.append((name, snap))
        for q in self._queues.values():
            q.append((name, copy.deepcopy(snap)))

    def subscribe(self, actor: str) -> list[tuple[str, dict]]:
        """Register an actor's watch queue (primed with the current
        state, like an informer's initial list) and return it."""
        q = [(n, copy.deepcopy(o)) for n, o in self._store.items()]
        self._queues[actor] = q
        return q

    def unsubscribe(self, actor: str) -> None:
        self._queues.pop(actor, None)

    def get(self, name: str) -> dict:
        if name not in self._store:
            raise NotFoundError(name)
        return copy.deepcopy(self._store[name])

    def names(self) -> list[str]:
        return sorted(self._store)

    def update(self, name: str, obj: dict) -> dict:
        if name not in self._store:
            raise NotFoundError(name)
        cur_rv = self._store[name]["metadata"]["resourceVersion"]
        rv_in = obj.get("metadata", {}).get("resourceVersion")
        if rv_in is not None and str(rv_in) != cur_rv:
            raise ConflictError(
                f"{name}: resourceVersion {rv_in} is stale "
                f"(current {cur_rv})")
        new = copy.deepcopy(obj)
        self._rv += 1
        new.setdefault("metadata", {})["resourceVersion"] = str(self._rv)
        new["metadata"].setdefault("name", name)
        self._store[name] = new
        self._broadcast(name)
        return copy.deepcopy(new)

    def patch(self, name: str, patch: dict) -> dict:
        if name not in self._store:
            raise NotFoundError(name)
        cur = self._store[name]
        cur_rv = cur["metadata"]["resourceVersion"]
        patch = copy.deepcopy(patch)
        rv_in = patch.get("metadata", {}).pop("resourceVersion", None)
        if rv_in is not None and str(rv_in) != cur_rv:
            raise ConflictError(
                f"{name}: resourceVersion {rv_in} is stale "
                f"(current {cur_rv})")

        def merge(dst, src):
            for k, v in src.items():
                if v is None:
                    dst.pop(k, None)
                elif isinstance(v, dict) and isinstance(dst.get(k), dict):
                    merge(dst[k], v)
                else:
                    dst[k] = copy.deepcopy(v)

        merge(cur, patch)
        self._rv += 1
        cur["metadata"]["resourceVersion"] = str(self._rv)
        self._broadcast(name)
        return copy.deepcopy(cur)


class ModelInformer:
    """An actor's local cache over its ModelApiServer watch queue.

    Nothing applies until the actor chooses to drain the queue -- which
    the scenarios surface as a scheduler ``choice()``: deliver all,
    deliver none (lag), or deliver all but the newest (a delayed tail,
    the coarse reorder model). Stale reads are therefore an explored
    branch, not a timing accident.
    """

    def __init__(self, api: ModelApiServer, actor: str):
        self.api = api
        self.actor = actor
        self.queue = api.subscribe(actor)
        self.cache: dict[str, dict] = {}

    def deliver(self, upto: int | None = None) -> int:
        """Apply the first ``upto`` queued events (all when None)."""
        n = len(self.queue) if upto is None else min(upto, len(self.queue))
        for name, obj in self.queue[:n]:
            self.cache[name] = obj
        del self.queue[:n]
        return n

    def get(self, name: str) -> dict | None:
        return self.cache.get(name)


class DurableCheckpoint:
    """A crash-surviving per-claim state dict whose every write is
    validated against a TransitionPolicy -- the model of the node
    plugins' group-committed CheckpointManager file. In-memory actor
    state dies with a modeled crash; this object is handed to the
    restarted incarnation, exactly like the on-disk checkpoint."""

    def __init__(self, policy):
        self.policy = policy
        self.states: dict[str, str] = {}

    def transition(self, uid: str, new: str | None) -> None:
        old = self.states.get(uid)
        self.policy.validate(uid, old, new)
        if new is None:
            self.states.pop(uid, None)
        else:
            self.states[uid] = new


# -- scenario: active-active commit protocol ----------------------------------


def _ledger_devices(ledger: dict) -> dict[str, str | None]:
    return ledger.get("spec", {}).get("devices", {})


def _status_keys(claim: dict) -> frozenset:
    # The REAL extractor the incremental scheduler state uses -- so the
    # invariant judges the exact claim shape production code consumes.
    return AllocationState._alloc_keys(claim)


def _stamp_patch(device: str) -> dict:
    return {"status": {"allocation": {"devices": {"results": [
        {"driver": DRIVER, "pool": POOL, "device": device},
    ]}}}}


class CommitScenario:
    """Two active-active schedulers share a placement domain: one
    ledger object (device -> claim, per-device node + watts, per-node
    power caps) arbitrates, each scheduler owns one pending claim, and
    both prefer the same device order -- so every schedule in which a
    stale read survives to the write is a potential double-allocation.

    ``precondition=False`` is the seeded bug: the ledger reservation
    becomes a blind merge-patch (no resourceVersion riding the write),
    i.e. exactly the defect lint rule TPUDRA018 pins in real code.

    Actor shape (per scheduler): up to ``rounds`` main rounds -- each a
    {deliver-choice, plan from cache, yield, reserve, stamp} sequence
    with optional crash seams -- then a deterministic, choice-free
    drain: resync from the apiserver, stamp orphan reservations, place
    own still-unplaced claims. The drain is what makes EVERY schedule
    converge under the correct protocol (the liveness half of the
    invariant set); it deliberately never second-guesses an
    already-stamped claim, so it cannot mask a double-stamp.
    """

    name = "commit"

    def __init__(self, precondition: bool = True, crashes: int = 0,
                 rounds: int = 2):
        self.precondition = precondition
        self.crash_budget = crashes
        self.rounds = rounds
        self.devices = {"d0": "n0", "d1": "n1"}  # device -> node
        self.watts = 100
        self.node_cap = 150  # one 100 W chip per node: overlap = over-commit
        self.claims = {"c0": "s0", "c1": "s1"}  # claim -> owning scheduler
        self.api: ModelApiServer | None = None
        self._crashes_left = 0

    # -- modeled objects ------------------------------------------------------

    def _initial_objects(self) -> dict[str, dict]:
        objs = {"ledger": {"spec": {
            "devices": {d: None for d in self.devices},
            "nodes": {d: n for d, n in self.devices.items()},
            "watts": {d: self.watts for d in self.devices},
            "caps": {n: self.node_cap for n in set(self.devices.values())},
        }}}
        for c in self.claims:
            objs[c] = {"metadata": {"name": c, "namespace": "default",
                                    "uid": f"uid-{c}"}, "status": {}}
        return objs

    # -- actor ---------------------------------------------------------------

    def _maybe_crash(self, sched: ControlledScheduler, actor: str,
                     seam: str) -> None:
        if self._crashes_left <= 0:
            return
        if sched.choice(2, f"{actor}:crash@{seam}") == 1:
            self._crashes_left -= 1
            raise _ActorCrash(f"{actor} @ {seam}")

    def _reserve(self, api: ModelApiServer, ledger: dict, device: str,
                 claim: str) -> bool:
        """One reservation write. Correct mode: full-object update
        riding the rv the plan read (409 = lost the race). Bug mode:
        blind merge-patch -- last writer silently wins the device."""
        if self.precondition:
            new = copy.deepcopy(ledger)
            _ledger_devices(new)[device] = claim
            try:
                api.update("ledger", new)
            except ConflictError:
                return False
            return True
        api.patch("ledger", {"spec": {"devices": {device: claim}}})
        return True

    def _stamp(self, api: ModelApiServer, claim: str, device: str) -> None:
        # Single writer per claim value-wise: every stamp derives from
        # the same immutable ledger entry, so the rv-less merge is
        # idempotent across the owner and any drain's orphan pass.
        try:
            api.patch(claim, _stamp_patch(device))
        except NotFoundError:
            pass

    def _drain(self, api: ModelApiServer, owned: list[str]) -> None:
        """Choice-free convergence pass (runs without yield points, so
        it executes atomically under the controlled scheduler): stamp
        any orphan reservation from ledger truth, then reserve+stamp
        own claims that have neither a stamp nor a ledger entry."""
        for _ in range(2 * len(self.claims) + 2):
            ledger = api.get("ledger")
            devs = _ledger_devices(ledger)
            placed = {c: d for d, c in devs.items() if c is not None}
            done = True
            for c in self.claims:
                claim = api.get(c)
                stamped = bool(_status_keys(claim))
                if not stamped and c in placed:
                    self._stamp(api, c, placed[c])  # orphan: crash seam hit
                    done = False
                elif not stamped and c in owned:
                    free = [d for d in sorted(devs) if devs[d] is None]
                    if not free:
                        continue
                    if self._reserve(api, ledger, free[0], c):
                        self._stamp(api, c, free[0])
                    done = False
            if done:
                return

    def _scheduler_body(self, sched: ControlledScheduler, api: ModelApiServer,
                        actor: str, owned: list[str]) -> None:
        inf = ModelInformer(api, actor)
        try:
            for _ in range(self.rounds):
                if inf.queue:
                    pick = sched.choice(3, f"{actor}:deliver")
                    if pick == 0:
                        inf.deliver()
                    elif pick == 2:
                        inf.deliver(len(inf.queue) - 1)  # delayed tail
                ledger = inf.get("ledger")
                if ledger is None:
                    continue
                devs = _ledger_devices(ledger)
                target = None
                for c in owned:
                    cached = inf.get(c)
                    if cached is not None and _status_keys(cached):
                        continue
                    if c in devs.values():
                        continue
                    free = [d for d in sorted(devs) if devs[d] is None]
                    if free:
                        target = (c, free[0])
                    break
                if target is None:
                    continue
                c, device = target
                self._maybe_crash(sched, actor, "pre-reserve")
                sched.yield_point(f"{actor}:write ledger")
                if self._reserve(api, ledger, device, c):
                    self._maybe_crash(sched, actor, "post-reserve")
                    sched.yield_point(f"{actor}:write {c}")
                    self._stamp(api, c, device)
            self._drain(api, owned)
        finally:
            api.unsubscribe(actor)

    def _actor(self, sched: ControlledScheduler, api: ModelApiServer,
               actor: str, owned: list[str]):
        def run() -> None:
            # Crash-restart loop: a modeled crash throws away ALL
            # in-memory state (informer cache included) and re-enters
            # the body, exactly like a process restart against the
            # durable apiserver. Bounded by the crash budget.
            for _ in range(self.crash_budget + 1):
                try:
                    self._scheduler_body(sched, api, actor, owned)
                    return
                except _ActorCrash:
                    sched.yield_point(f"{actor}:restart")
            self._drain(api, owned)
        return run

    # -- explore() adapter ----------------------------------------------------

    def build(self, sched: ControlledScheduler) -> None:
        self.api = ModelApiServer(self._initial_objects())
        self._crashes_left = self.crash_budget
        by_owner: dict[str, list[str]] = {}
        for c, s in self.claims.items():
            by_owner.setdefault(s, []).append(c)
        for actor in sorted(by_owner):
            sched.spawn(self._actor(sched, self.api, actor,
                                    sorted(by_owner[actor])), name=actor)

    def invariant(self, sched: ControlledScheduler) -> None:
        api = self.api
        assert api is not None
        ledger = api.get("ledger")
        devs = _ledger_devices(ledger)
        statuses = {c: api.get(c) for c in self.claims}
        keys = {c: _status_keys(obj) for c, obj in statuses.items()}

        # No double-allocation: pairwise-disjoint status device keys.
        names = sorted(keys)
        for i, a in enumerate(names):
            for b in names[i + 1:]:
                overlap = keys[a] & keys[b]
                assert not overlap, (
                    f"double-allocation: {sorted(k[2] for k in overlap)} "
                    f"held by both {a} and {b}")

        # Convergence: every claim stamped and ledger-backed, both ways.
        placed = {c: d for d, c in devs.items() if c is not None}
        for c in self.claims:
            assert keys[c], f"claim {c} never converged (no allocation)"
            stamped = {k[2] for k in keys[c]}
            assert c in placed and placed[c] in stamped, (
                f"ledger/status divergence: {c} stamped {sorted(stamped)} "
                f"but ledger places it on {placed.get(c)!r}")

        # Power ledger: per-node debit of status-referenced chips must
        # fit the node cap (a double-allocated chip debits twice).
        caps = ledger["spec"]["caps"]
        nodes = ledger["spec"]["nodes"]
        watts = ledger["spec"]["watts"]
        used: dict[str, int] = {}
        for c in names:
            for k in keys[c]:
                node = nodes.get(k[2], "?")
                used[node] = used.get(node, 0) + watts.get(k[2], 0)
        for node, total in used.items():
            assert total <= caps.get(node, 0), (
                f"power over-commit on {node}: {total} W debited, "
                f"cap {caps.get(node, 0)} W")


# -- scenario: two-phase prepare under crashes --------------------------------


class PrepareScenario:
    """A node plugin on node n0 runs the two-phase prepare
    (PrepareStarted durable -> work -> PrepareCompleted) for the claims
    allocated on ITS node, against a DurableCheckpoint that survives
    modeled crashes, resuming half-done prepares on restart; a
    scheduler concurrently places a second claim (which lands on the
    OTHER node -- a plugin only ever prepares node-local claims, so its
    convergence obligation is exactly the set allocated on n0). Every
    checkpoint write is policy-validated inline, so an illegal
    transition anywhere on the crash/resume lattice is a failure with a
    replayable schedule -- the dynamic twin of the static
    ``crash_closure`` pass."""

    name = "prepare"
    node = "n0"

    def __init__(self, crashes: int = 1):
        self.crash_budget = crashes
        self.commit = CommitScenario(precondition=True, crashes=0, rounds=1)
        self.commit.claims = {"c0": "s0", "c1": "s0"}
        self.checkpoint: DurableCheckpoint | None = None
        self._crashes_left = 0

    def _initial_objects(self) -> dict[str, dict]:
        # c0 starts placed+stamped on the plugin's node: a node plugin
        # has work exactly when its node has allocations, so the
        # prepare lattice is explored without coupling the plugin's
        # liveness to the placement race (CommitScenario owns that).
        objs = self.commit._initial_objects()
        _ledger_devices(objs["ledger"])["d0"] = "c0"
        stamped = claim_like("c0", [(DRIVER, POOL, "d0")], uid="uid-c0")
        objs["c0"]["status"] = stamped["status"]
        return objs

    def _local_uids(self, read) -> list[tuple[str, str]]:
        """(claim, uid) pairs whose allocation references a device on
        the plugin's node, per ``read(name) -> obj | None``."""
        out = []
        for c in sorted(self.commit.claims):
            obj = read(c)
            if obj is None:
                continue
            for k in _status_keys(obj):
                if self.commit.devices.get(k[2]) == self.node:
                    out.append((c, obj["metadata"].get("uid", c)))
                    break
        return out

    def _prepare_one(self, sched: ControlledScheduler,
                     cp: DurableCheckpoint, uid: str) -> None:
        if cp.states.get(uid) == PREPARE_COMPLETED:
            return
        if cp.states.get(uid) != PREPARE_STARTED:
            cp.transition(uid, PREPARE_STARTED)  # durable reservation
        if self._crashes_left > 0 and sched.choice(
                2, "plugin:crash@mid-prepare") == 1:
            self._crashes_left -= 1
            raise _ActorCrash("plugin mid-prepare")
        sched.yield_point(f"plugin:complete {uid}")
        cp.transition(uid, PREPARE_COMPLETED)

    def _plugin_body(self, sched: ControlledScheduler, api: ModelApiServer,
                     cp: DurableCheckpoint) -> None:
        inf = ModelInformer(api, "plugin")
        try:
            # Resume path first, like a restarted kubelet plugin: any
            # durable PrepareStarted must be driven to completion
            # before new work (the crash-closure contract, dynamic).
            for uid, state in sorted(cp.states.items()):
                if state == PREPARE_STARTED:
                    sched.yield_point(f"plugin:resume {uid}")
                    cp.transition(uid, PREPARE_COMPLETED)
            for _ in range(2):
                if inf.queue:
                    if sched.choice(2, "plugin:deliver") == 0:
                        inf.deliver()
                for _, uid in self._local_uids(inf.get):
                    self._prepare_one(sched, cp, uid)
            # Drain: finish every node-local claim from apiserver truth.
            for _, uid in self._local_uids(
                    lambda c: api.get(c)):
                self._prepare_one(sched, cp, uid)
        finally:
            api.unsubscribe("plugin")

    def build(self, sched: ControlledScheduler) -> None:
        self.commit.api = ModelApiServer(self._initial_objects())
        self.commit._crashes_left = 0
        api = self.commit.api
        sched.spawn(self.commit._actor(sched, api, "s0", ["c0", "c1"]),
                    name="s0")
        self.checkpoint = DurableCheckpoint(TWO_PHASE_POLICY)
        self._crashes_left = self.crash_budget

        def plugin() -> None:
            cp = self.checkpoint  # durable: same object across restarts
            for _ in range(self.crash_budget + 1):
                try:
                    self._plugin_body(sched, api, cp)
                    return
                except _ActorCrash:
                    sched.yield_point("plugin:restart")
            # Out of restart budget: still owe the drain (the modeled
            # "eventually the plugin stays up" assumption).
            for _, uid in self._local_uids(lambda c: api.get(c)):
                if cp.states.get(uid) != PREPARE_COMPLETED:
                    if cp.states.get(uid) != PREPARE_STARTED:
                        cp.transition(uid, PREPARE_STARTED)
                    cp.transition(uid, PREPARE_COMPLETED)

        sched.spawn(plugin, name="plugin")

    def invariant(self, sched: ControlledScheduler) -> None:
        self.commit.invariant(sched)
        cp = self.checkpoint
        api = self.commit.api
        assert cp is not None and api is not None
        local = self._local_uids(lambda c: api.get(c))
        assert local, "model bug: no claim ended on the plugin's node"
        for c, uid in local:
            assert cp.states.get(uid) == PREPARE_COMPLETED, (
                f"allocated claim {c} ended {cp.states.get(uid) or 'absent'}"
                " in the node checkpoint (prepare never completed)")
        for uid, state in cp.states.items():
            assert state in (PREPARE_STARTED, PREPARE_COMPLETED), (
                f"checkpoint holds unknown state {state!r} for {uid}")


# -- scenario: recovery/eviction ladder under crashes -------------------------


class RecoveryScenario:
    """A claim sits allocated on a device that then fails. The recovery
    controller walks the EVICTION_POLICY ladder (Planned -> Draining:
    clear the claim status -> Deallocated: free the ledger slot with an
    rv precondition -> absent), persisting each rung in a
    DurableCheckpoint so a crash at any seam resumes idempotently from
    the durable rung; its final drain re-places the claim on a healthy
    device. A contending scheduler runs benign rounds alongside (its
    drain must neither resurrect the failed device nor stamp the
    half-evicted orphan)."""

    name = "recovery"

    def __init__(self, crashes: int = 1):
        self.crash_budget = crashes
        self.commit = CommitScenario(precondition=True, crashes=0, rounds=1)
        self.commit.claims = {"c0": "recovery"}
        self.failed_device = "d0"
        self.checkpoint: DurableCheckpoint | None = None
        self._crashes_left = 0

    def _initial_objects(self) -> dict[str, dict]:
        objs = self.commit._initial_objects()
        # c0 starts placed+stamped on the device that is about to fail.
        _ledger_devices(objs["ledger"])[self.failed_device] = "c0"
        stamped = claim_like(
            "c0", [(DRIVER, POOL, self.failed_device)], uid="uid-c0")
        objs["c0"]["status"] = stamped["status"]
        objs["ledger"]["spec"]["failed"] = [self.failed_device]
        return objs

    def _maybe_crash(self, sched: ControlledScheduler, seam: str) -> None:
        if self._crashes_left <= 0:
            return
        if sched.choice(2, f"recovery:crash@{seam}") == 1:
            self._crashes_left -= 1
            raise _ActorCrash(f"recovery @ {seam}")

    def _controller_body(self, sched: ControlledScheduler,
                         api: ModelApiServer, cp: DurableCheckpoint) -> None:
        uid = "uid-c0"
        # Resume from whatever rung the durable record holds -- each
        # arm is idempotent, so a crash-restart redoes at most one.
        if cp.states.get(uid) is None:
            sched.yield_point("recovery:plan")
            cp.transition(uid, EVICTION_PLANNED)
            self._maybe_crash(sched, "planned")
        if cp.states.get(uid) == EVICTION_PLANNED:
            sched.yield_point("recovery:write c0")
            api.patch("c0", {"status": {"allocation": None}})
            cp.transition(uid, EVICTION_DRAINING)
            self._maybe_crash(sched, "draining")
        if cp.states.get(uid) == EVICTION_DRAINING:
            for _ in range(4):
                ledger = api.get("ledger")
                devs = _ledger_devices(ledger)
                if devs.get(self.failed_device) != "c0":
                    break
                new = copy.deepcopy(ledger)
                _ledger_devices(new)[self.failed_device] = None
                sched.yield_point("recovery:write ledger")
                try:
                    api.update("ledger", new)
                    break
                except ConflictError:
                    continue
            cp.transition(uid, EVICTION_DEALLOCATED)
            self._maybe_crash(sched, "deallocated")
        if cp.states.get(uid) == EVICTION_DEALLOCATED:
            cp.transition(uid, None)
        # Re-placement drain: the controller owns convergence here.
        self._healthy_drain(api, ["c0"])

    def _healthy_drain(self, api: ModelApiServer, owned: list[str]) -> None:
        """CommitScenario._drain with the failed-device guard: never
        reserve a failed device, never stamp an orphan ledger entry
        that still points at one (it is mid-eviction, not recoverable
        truth)."""
        for _ in range(6):
            ledger = api.get("ledger")
            devs = _ledger_devices(ledger)
            failed = set(ledger["spec"].get("failed", []))
            placed = {c: d for d, c in devs.items()
                      if c is not None and d not in failed}
            done = True
            for c in self.commit.claims:
                claim = api.get(c)
                if _status_keys(claim):
                    continue
                if c in placed:
                    self.commit._stamp(api, c, placed[c])
                    done = False
                elif c in owned and c not in {
                        v for d, v in devs.items() if v is not None}:
                    free = [d for d in sorted(devs)
                            if devs[d] is None and d not in failed]
                    if not free:
                        continue
                    if self.commit._reserve(api, ledger, free[0], c):
                        self.commit._stamp(api, c, free[0])
                    done = False
            if done:
                return

    def build(self, sched: ControlledScheduler) -> None:
        self.commit.api = ModelApiServer(self._initial_objects())
        api = self.commit.api
        self.checkpoint = DurableCheckpoint(EVICTION_POLICY)
        self._crashes_left = self.crash_budget

        def controller() -> None:
            cp = self.checkpoint
            for _ in range(self.crash_budget + 1):
                try:
                    self._controller_body(sched, api, cp)
                    return
                except _ActorCrash:
                    sched.yield_point("recovery:restart")
            self._healthy_drain(api, ["c0"])

        def bystander() -> None:
            # A contending scheduler: resyncs and runs the guarded
            # drain for claims it does NOT own -- it may stamp a
            # healthy orphan but must never touch the failed device.
            for _ in range(2):
                sched.yield_point("s1:read ledger")
            self._healthy_drain(api, [])

        sched.spawn(controller, name="recovery")
        sched.spawn(bystander, name="s1")

    def invariant(self, sched: ControlledScheduler) -> None:
        api = self.commit.api
        cp = self.checkpoint
        assert api is not None and cp is not None
        ledger = api.get("ledger")
        failed = set(ledger["spec"].get("failed", []))
        claim = api.get("c0")
        keys = _status_keys(claim)
        assert keys, "c0 never re-placed after eviction"
        stamped = {k[2] for k in keys}
        assert not (stamped & failed), (
            f"c0 re-placed onto failed device(s) {sorted(stamped & failed)}")
        devs = _ledger_devices(ledger)
        placed = {c: d for d, c in devs.items() if c is not None}
        assert placed.get("c0") in stamped, (
            f"ledger/status divergence after recovery: ledger "
            f"{placed.get('c0')!r} vs status {sorted(stamped)}")
        assert not cp.states, (
            f"eviction checkpoint not drained: {cp.states}")


# -- scenario: cooperative migration handshake --------------------------------


class MigrationScenario:
    """A claim sits allocated on a source device and the migration
    controller walks the cooperative checkpoint-then-switch handshake
    (pkg/migration) against it: reserve a destination FIRST (a ledger
    marker written with an rv precondition -- the modeled
    reservation-veto), signal the workload via a claim annotation, wait
    for the checkpoint ack, then switch (free the source + convert the
    reservation into the allocation in one preconditioned write) and
    re-stamp. Every rung persists in a DurableCheckpoint under
    MIGRATION_POLICY, so a crash at any seam resumes idempotently.

    The explored adversaries: a STALE plan read (informer delivery
    choice), an arbitrarily DELAYED (or never-arriving) workload ack, a
    controller CRASH-RESTART at every post-transition seam, a RACING
    CLAIM DELETE (deletionTimestamp tombstone), and a contending
    scheduler placing its own claim into the same pool. The invariant
    set is the robustness contract: no leaked reservation marker, no
    drained-but-present record, no double allocation, the undeleted
    claim always ends allocated (source OR destination -- a fallback
    never strands it), and the bystander claim always converges."""

    name = "migration"

    RESERVED = "!c0"  # ledger marker: destination held for the move

    def __init__(self, crashes: int = 1):
        self.crash_budget = crashes
        self.commit = CommitScenario(precondition=True, crashes=0,
                                     rounds=1)
        self.commit.devices = {"d0": "n0", "d1": "n1", "d2": "n2"}
        self.commit.claims = {"c1": "s1"}
        self.source = "d0"
        self.checkpoint: DurableCheckpoint | None = None
        # The durable record's live payload (the planned target): hands
        # over to a restarted incarnation exactly like the on-disk
        # record, while all other controller state dies with the crash.
        self.durable: dict[str, str] = {}
        self._crashes_left = 0

    def _initial_objects(self) -> dict[str, dict]:
        objs = self.commit._initial_objects()
        objs["c0"] = {"metadata": {"name": "c0", "namespace": "default",
                                   "uid": "uid-c0"}, "status": {}}
        _ledger_devices(objs["ledger"])[self.source] = "c0"
        stamped = claim_like(
            "c0", [(DRIVER, POOL, self.source)], uid="uid-c0")
        objs["c0"]["status"] = stamped["status"]
        return objs

    def _maybe_crash(self, sched: ControlledScheduler, seam: str) -> None:
        if self._crashes_left <= 0:
            return
        if sched.choice(2, f"migration:crash@{seam}") == 1:
            self._crashes_left -= 1
            raise _ActorCrash(f"migration @ {seam}")

    def _claim_deleted(self, api: ModelApiServer) -> bool:
        try:
            return bool(api.get("c0")["metadata"].get(
                "deletionTimestamp"))
        except NotFoundError:
            return True

    def _cancel(self, sched: ControlledScheduler, api: ModelApiServer,
                cp: DurableCheckpoint) -> None:
        """The guaranteed cold path, legal from every rung
        (MIGRATION_POLICY allows state -> absent everywhere): release
        the reservation marker, drop any ledger slot a DELETED claim
        still holds, clear the contract annotations, retire the
        record. An undeleted claim keeps its source allocation -- the
        workload was never stopped, so fallback must not disturb it."""
        for attempt in range(8):
            ledger = api.get("ledger")
            devs = _ledger_devices(ledger)
            gone = self._claim_deleted(api)
            dirty = [d for d, v in devs.items()
                     if v == self.RESERVED or (gone and v == "c0")]
            if not dirty:
                break
            new = copy.deepcopy(ledger)
            for d in dirty:
                _ledger_devices(new)[d] = None
            if attempt == 0:
                sched.yield_point("migration:write ledger")
            try:
                api.update("ledger", new)
                break
            except ConflictError:
                continue
        try:
            api.patch("c0", {"metadata": {"annotations": {
                "intent": None, "ack": None}}})
        except NotFoundError:
            pass
        if cp.states.get("uid-c0") is not None:
            cp.transition("uid-c0", None)
        self.durable.pop("target", None)

    def _controller_body(self, sched: ControlledScheduler,
                         api: ModelApiServer,
                         cp: DurableCheckpoint) -> None:
        uid = "uid-c0"
        if cp.states.get(uid) is None:
            # Plan against a possibly-STALE informer read: the delivery
            # choice decides how much of the watch stream the plan saw.
            inf = ModelInformer(api, "migration-inf")
            pick = sched.choice(3, "migration:deliver")
            if pick == 0:
                inf.deliver()
            elif pick == 2:
                inf.deliver(max(len(inf.queue) - 1, 0))
            api.unsubscribe("migration-inf")
            ledger = inf.get("ledger") or api.get("ledger")
            devs = _ledger_devices(ledger)
            free = [d for d in sorted(devs)
                    if devs[d] is None and d != self.source]
            if not free:
                return  # nothing reservable: defer, claim undisturbed
            # Reserve-first: the durable record (with its target) IS
            # the reservation; the ledger marker is re-derived from it
            # on every resume, so a crash here cannot leak anything.
            self.durable["target"] = free[0]
            cp.transition(uid, MIGRATION_DEST_RESERVED)
            self._maybe_crash(sched, "reserve")
        target = self.durable.get("target", "")
        if cp.states.get(uid) == MIGRATION_DEST_RESERVED:
            # Pin the marker with an rv precondition. A stale plan
            # loses the race here and cancels: reserve-first means
            # nothing was disrupted yet, so deferral is free.
            pinned = False
            for _ in range(8):
                ledger = api.get("ledger")
                devs = _ledger_devices(ledger)
                if devs.get(target) == self.RESERVED:
                    pinned = True
                    break
                if devs.get(target) is not None:
                    break  # destination raced away
                new = copy.deepcopy(ledger)
                _ledger_devices(new)[target] = self.RESERVED
                sched.yield_point("migration:write ledger")
                try:
                    api.update("ledger", new)
                    pinned = True
                    break
                except ConflictError:
                    continue
            if not pinned or self._claim_deleted(api):
                self._cancel(sched, api, cp)
                return
            sched.yield_point("migration:write c0")
            try:
                api.patch("c0", {"metadata": {"annotations": {
                    "intent": target}}})
            except NotFoundError:
                self._cancel(sched, api, cp)
                return
            cp.transition(uid, MIGRATION_INTENT_SIGNALED)
            self._maybe_crash(sched, "signal")
        if cp.states.get(uid) == MIGRATION_INTENT_SIGNALED:
            acked = False
            for _ in range(6):
                if self._claim_deleted(api):
                    self._cancel(sched, api, cp)  # racing delete: cancel
                    return
                claim = api.get("c0")
                if ((claim["metadata"].get("annotations") or {})
                        .get("ack")):
                    acked = True
                    break
                sched.yield_point("migration:read c0")
            if not acked:
                self._cancel(sched, api, cp)  # ack timeout: cold fallback
                return
            cp.transition(uid, MIGRATION_WORKLOAD_ACKED)
            self._maybe_crash(sched, "ack")
        if cp.states.get(uid) == MIGRATION_WORKLOAD_ACKED:
            if self._claim_deleted(api):
                self._cancel(sched, api, cp)
                return
            cp.transition(uid, MIGRATION_SWITCHING)
            self._maybe_crash(sched, "switch")
        if cp.states.get(uid) == MIGRATION_SWITCHING:
            # The switch: ONE preconditioned ledger write frees the
            # source and converts the reservation into the allocation;
            # then the claim re-stamps onto the destination. Each arm
            # is idempotent for the crash-resume path.
            for _ in range(8):
                ledger = api.get("ledger")
                devs = _ledger_devices(ledger)
                if devs.get(self.source) != "c0" and \
                        devs.get(target) == "c0":
                    break  # a previous incarnation already switched
                new = copy.deepcopy(ledger)
                nd = _ledger_devices(new)
                if nd.get(self.source) == "c0":
                    nd[self.source] = None
                nd[target] = "c0"
                sched.yield_point("migration:write ledger")
                try:
                    api.update("ledger", new)
                    break
                except ConflictError:
                    continue
            sched.yield_point("migration:write c0")
            try:
                api.patch("c0", {"metadata": {"annotations": {
                    "intent": None, "ack": None}}, "status": None})
                api.patch("c0", _stamp_patch(target))
            except NotFoundError:
                pass
            cp.transition(uid, None)
            self.durable.pop("target", None)
            if self._claim_deleted(api):
                self._cancel(sched, api, cp)  # deleted mid-switch: scrub

    def build(self, sched: ControlledScheduler) -> None:
        self.commit.api = ModelApiServer(self._initial_objects())
        api = self.commit.api
        self.checkpoint = DurableCheckpoint(MIGRATION_POLICY)
        self.durable = {}
        self._crashes_left = self.crash_budget

        def controller() -> None:
            cp = self.checkpoint
            for _ in range(self.crash_budget + 1):
                try:
                    self._controller_body(sched, api, cp)
                    return
                except _ActorCrash:
                    sched.yield_point("migration:restart")
            self._cancel(sched, api, cp)  # budget exhausted: cold path

        def workload() -> None:
            # The migration-capable workload: watches for the intent
            # annotation through its OWN (choice-delayed) informer,
            # checkpoints, acks. May never see the intent within its
            # run -- that schedule exercises the ack-timeout fallback.
            inf = ModelInformer(api, "workload")
            try:
                for _ in range(5):
                    if inf.queue:
                        pick = sched.choice(3, "workload:deliver")
                        if pick == 0:
                            inf.deliver()
                        elif pick == 2:
                            inf.deliver(len(inf.queue) - 1)
                    cached = inf.get("c0")
                    ann = ((cached or {}).get("metadata") or {}).get(
                        "annotations") or {}
                    if ann.get("intent"):
                        if sched.choice(2, "workload:ack-delay") == 1:
                            sched.yield_point("workload:checkpointing")
                        sched.yield_point("workload:write c0")
                        try:
                            api.patch("c0", {"metadata": {
                                "annotations": {"ack": "ok"}}})
                        except NotFoundError:
                            pass
                        return
                    sched.yield_point("workload:idle")
            finally:
                api.unsubscribe("workload")

        def deleter() -> None:
            # The racing claim delete, as an explored branch: a
            # tombstone patch (the model's deletionTimestamp) at
            # whatever point the schedule lands it, followed by the
            # SCHEDULER'S deleted-claim sweep (folded into this actor:
            # a deleted claim's ledger slots are reclaimed by the
            # allocation owner, while the reservation marker stays the
            # migration controller's to release).
            if sched.choice(2, "deleter:delete") != 1:
                return
            sched.yield_point("deleter:write c0")
            api.patch("c0", {"metadata": {
                "deletionTimestamp": "T0"}})
            for attempt in range(8):
                ledger = api.get("ledger")
                devs = _ledger_devices(ledger)
                dirty = [d for d, v in devs.items() if v == "c0"]
                if not dirty:
                    return
                new = copy.deepcopy(ledger)
                for d in dirty:
                    _ledger_devices(new)[d] = None
                if attempt == 0:
                    sched.yield_point("deleter:write ledger")
                try:
                    api.update("ledger", new)
                    return
                except ConflictError:
                    continue

        def bystander() -> None:
            # A contending scheduler placing c1 into the same pool:
            # the reservation marker must veto it off the destination.
            self.commit._scheduler_body(sched, api, "s1", ["c1"])

        sched.spawn(controller, name="migration")
        sched.spawn(workload, name="workload")
        sched.spawn(deleter, name="deleter")
        sched.spawn(bystander, name="s1")

    def invariant(self, sched: ControlledScheduler) -> None:
        api = self.commit.api
        cp = self.checkpoint
        assert api is not None and cp is not None
        ledger = api.get("ledger")
        devs = _ledger_devices(ledger)
        # No leaked destination reservation, no undrained record.
        leaked = [d for d, v in devs.items() if v == self.RESERVED]
        assert not leaked, f"leaked destination reservation on {leaked}"
        assert not cp.states, (
            f"migration record not drained: {cp.states}")
        # The bystander claim converged, ledger-consistently.
        self.commit.invariant(sched)
        c0 = api.get("c0")
        placed = {c: d for d, c in devs.items() if c is not None}
        if c0["metadata"].get("deletionTimestamp"):
            assert "c0" not in placed, (
                f"deleted claim c0 still holds ledger slot "
                f"{placed.get('c0')!r}")
            return
        # The undeleted claim is never stranded: it ends allocated on
        # source OR destination, status and ledger agreeing, disjoint
        # from the bystander.
        keys = _status_keys(c0)
        assert keys, "c0 lost its allocation without being deleted"
        stamped = {k[2] for k in keys}
        assert placed.get("c0") in stamped, (
            f"ledger/status divergence: c0 stamped {sorted(stamped)} "
            f"but ledger places it on {placed.get('c0')!r}")
        c1_keys = _status_keys(api.get("c1"))
        overlap = keys & c1_keys
        assert not overlap, (
            f"double-allocation: {sorted(k[2] for k in overlap)} held "
            f"by both c0 and c1")


SCENARIOS = {
    "commit": CommitScenario,
    "prepare": PrepareScenario,
    "recovery": RecoveryScenario,
    "migration": MigrationScenario,
}


# -- partial-order reduction --------------------------------------------------


def _op_parts(label: str) -> tuple[str, str]:
    """Split an option label into (actor, operation). Labels this
    module emits are ``actor:op ...``; anything else (lock labels from
    interleave instrumentation, bare yields) degrades to ('', label)
    and is judged dependent -- conservative by construction."""
    if ":" in label:
        actor, _, op = label.partition(":")
        if " " not in actor and actor:
            return actor, op
    return "", label


def independent_ops(a: str, b: str) -> bool:
    """Conservative commutation judgment for explore()'s sleep-set
    pruning. Two parked operations commute only when they belong to
    DIFFERENT actors and neither can observe the other:

    - both are apiserver writes to DIFFERENT objects, or
    - one is a pure-local start/read and the other actor's op touches
      no shared object it reads.

    Everything involving watch delivery, crashes, restarts, or the same
    apiserver object is dependent (deliveries observe every prior
    write; crash options change enabled-ness). When unsure: False --
    see docs/analysis.md "POR caveats"."""
    actor_a, op_a = _op_parts(a)
    actor_b, op_b = _op_parts(b)
    if not actor_a or not actor_b or actor_a == actor_b:
        return False
    for op in (op_a, op_b):
        if not (op.startswith("write ") or op.startswith("read ")):
            return False
    obj_a = op_a.split(" ", 1)[1]
    obj_b = op_b.split(" ", 1)[1]
    if op_a.startswith("read ") and op_b.startswith("read "):
        return True
    return obj_a != obj_b


# -- failure minimization + replay --------------------------------------------


def minimize_failure(scenario, choices: list[int], error_type: str,
                     max_probes: int = 400) -> tuple[list[int], int]:
    """Shrink a failing choice list while the SAME failure class
    reproduces: drop the tail, then zero individual choices (0 is every
    chooser's default), to fixpoint or probe budget. Returns (minimized
    choices, probes spent). Deterministic: every probe is a
    ReplayChooser run of the scenario."""
    probes = 0

    def fails(cand: list[int]) -> bool:
        nonlocal probes
        probes += 1
        _, err = _run_one(scenario.build, scenario.invariant,
                          ReplayChooser(cand))
        return err is not None and type(err).__name__ == error_type

    best = list(choices)
    changed = True
    while changed and probes < max_probes:
        changed = False
        while best and probes < max_probes and fails(best[:-1]):
            best = best[:-1]
            changed = True
        for i in range(len(best)):
            if probes >= max_probes:
                break
            if best[i] == 0:
                continue
            cand = best[:i] + [0] + best[i + 1:]
            if fails(cand):
                best = cand
                changed = True
    return best, probes


def make_artifact(scenario, failure) -> dict:
    return {
        "scenario": scenario.name,
        "params": {
            "precondition": getattr(scenario, "precondition",
                                    getattr(getattr(scenario, "commit", None),
                                            "precondition", True)),
            "crashes": getattr(scenario, "crash_budget", 0),
        },
        "choices": list(failure.choices),
        "error_type": type(failure.error).__name__,
        "error": str(failure.error),
        "trace": [list(t) for t in failure.trace],
    }


def replay_artifact(artifact: dict):
    """Re-run a recorded failing schedule deterministically. Returns
    (scheduler, error) -- error is None when the schedule no longer
    fails (i.e. the bug is fixed)."""
    cls = SCENARIOS[artifact["scenario"]]
    params = artifact.get("params", {})
    if cls is CommitScenario:
        scenario = cls(precondition=params.get("precondition", True),
                       crashes=params.get("crashes", 0))
    else:
        scenario = cls(crashes=params.get("crashes", 0))
    return _run_one(scenario.build, scenario.invariant,
                    ReplayChooser(list(artifact["choices"])))


# -- gates --------------------------------------------------------------------


def check_seeded_bug(max_schedules: int = 400) -> dict:
    """The self-test: with the resourceVersion precondition REMOVED
    from the ledger reservation, bounded DFS must find a
    double-allocation, minimize it, and the minimized schedule must
    replay to the same failure."""
    scenario = CommitScenario(precondition=False)
    res = explore(scenario.build, scenario.invariant,
                  max_schedules=max_schedules, stop_at_first_failure=True,
                  independent=independent_ops)
    out = {"gate": "seeded-bug", "schedules_run": res.schedules_run,
           "caught": bool(res.failures), "ok": bool(res.failures)}
    if not res.failures:
        return out
    failure = res.failures[0]
    error_type = type(failure.error).__name__
    minimized, probes = minimize_failure(scenario, failure.choices,
                                         error_type)
    _, err = _run_one(scenario.build, scenario.invariant,
                      ReplayChooser(minimized))
    replay_ok = err is not None and type(err).__name__ == error_type
    failure.choices = minimized
    artifact = make_artifact(scenario, failure)
    artifact["error"] = str(err) if replay_ok else artifact["error"]
    out.update({
        "minimized_choices": minimized,
        "minimize_probes": probes,
        "replay_deterministic": replay_ok,
        "artifact": artifact,
        "error": artifact["error"],
        "ok": replay_ok,
    })
    return out


def _result_dict(gate: str, res: ExplorationResult) -> dict:
    return {
        "gate": gate,
        "schedules_run": res.schedules_run,
        "exhausted": res.exhausted,
        "failures": [
            {"choices": f.choices,
             "error_type": type(f.error).__name__,
             "error": str(f.error)}
            for f in res.failures[:5]
        ],
        "ok": res.ok,
    }


def check_scenario(name: str, dfs: int, rand: int, seed: int = 0,
                   crashes: int = 0) -> dict:
    """Correct-protocol gate: DFS + seeded-random exploration of one
    scenario must report ZERO violations."""
    def fresh():
        cls = SCENARIOS[name]
        if cls is CommitScenario:
            return cls(precondition=True, crashes=crashes)
        return cls(crashes=crashes)

    scenario = fresh()
    res = explore(scenario.build, scenario.invariant, max_schedules=dfs,
                  independent=independent_ops)
    total = _result_dict(f"{name}(crashes={crashes})", res)
    if rand > 0:
        scenario = fresh()
        rres = explore_random(scenario.build, scenario.invariant,
                              schedules=rand, seed=seed)
        total["schedules_run"] += rres.schedules_run
        total["random_schedules"] = rres.schedules_run
        total["failures"] += [
            {"choices": f.choices, "error_type": type(f.error).__name__,
             "error": str(f.error)} for f in rres.failures[:5]]
        total["ok"] = total["ok"] and rres.ok
    return total


def run_gates(full: bool = False, seed: int = 0,
              schedules: int | None = None) -> dict:
    """The composite gate ``make modelcheck-smoke`` / ``modelcheck``
    run. Smoke: seconds. Full: >= 10k correct-protocol schedules."""
    if schedules is None:
        schedules = 12_000 if full else 1_200
    half = schedules // 2
    gates = [check_seeded_bug(max_schedules=600 if full else 400)]
    gates.append(check_scenario("commit", dfs=half, rand=schedules - half,
                                seed=seed))
    crash_budget = schedules // 6 if full else 300
    gates.append(check_scenario("commit", dfs=crash_budget,
                                rand=crash_budget // 2, seed=seed + 1,
                                crashes=1))
    gates.append(check_scenario("prepare", dfs=crash_budget,
                                rand=crash_budget // 2, seed=seed + 2,
                                crashes=1))
    gates.append(check_scenario("recovery", dfs=crash_budget,
                                rand=crash_budget // 2, seed=seed + 3,
                                crashes=1))
    gates.append(check_scenario("migration", dfs=crash_budget,
                                rand=crash_budget // 2, seed=seed + 4,
                                crashes=1))
    closure = crash_closure_all()
    gates.append({"gate": "crash-closure", "ok": closure["ok"],
                  "policies": {n: {"unreachable": p["unreachable"],
                                   "unresumable": p["unresumable"]}
                               for n, p in closure["policies"].items()}})
    return {"mode": "full" if full else "smoke",
            "ok": all(g["ok"] for g in gates),
            "schedules_total": sum(g.get("schedules_run", 0) for g in gates),
            "gates": gates}


def _print_report(report: dict) -> None:
    for g in report["gates"]:
        status = "ok" if g["ok"] else "FAIL"
        extra = ""
        if g["gate"] == "seeded-bug":
            extra = (f" caught={g['caught']}"
                     f" minimized={len(g.get('minimized_choices', []))}"
                     f" choices replay={g.get('replay_deterministic')}")
        elif "schedules_run" in g:
            extra = (f" schedules={g['schedules_run']}"
                     f" exhausted={g.get('exhausted')}")
        print(f"  [{status}] {g['gate']}{extra}")
        for f in g.get("failures", []):
            print(f"         {f['error_type']}: {f['error']}")
            print(f"         replay choices: {f['choices']}")
    total = report.get("schedules_total", 0)
    print(f"modelcheck {report['mode']}: "
          f"{'PASS' if report['ok'] else 'FAIL'} "
          f"({total} schedules explored)")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m k8s_dra_driver_gpu_tpu.pkg.analysis.modelcheck",
        description="Multi-actor protocol model checker "
                    "(docs/analysis.md, 'Model checking the commit "
                    "protocol').")
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--smoke", action="store_true",
                      help="CI gate: bounded DFS+random, seconds")
    mode.add_argument("--full", action="store_true",
                      help="pre-release gate: >= 10k schedules")
    mode.add_argument("--replay", metavar="ARTIFACT",
                      help="re-run a recorded failing schedule")
    ap.add_argument("--schedules", type=int, default=None,
                    help="override the correct-protocol schedule budget")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json-out", metavar="PATH",
                    help="write the machine-readable report/artifact here")
    args = ap.parse_args(argv)

    if args.replay:
        with open(args.replay, encoding="utf-8") as f:
            artifact = json.load(f)
        sched, err = replay_artifact(artifact)
        if err is None:
            print(f"replay of {artifact['scenario']} schedule "
                  f"{artifact['choices']}: no longer fails")
            return 0
        print(f"replay reproduces {type(err).__name__}: {err}")
        for name, label in sched.trace:
            print(f"  {name}: {label}")
        return 1

    report = run_gates(full=args.full, seed=args.seed,
                       schedules=args.schedules)
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
    _print_report(report)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
