"""Concurrency invariant analysis for the TPU DRA driver.

The locking hierarchy and checkpoint state machine that PR 1/PR 2
introduced (docs/architecture.md "Locking hierarchy") live here as
*checked* artifacts instead of prose:

- ``lint``: an AST-based lock-hierarchy linter (rule IDs TPUDRA001..)
  with a committed baseline-suppression file -- the ``go vet`` analog
  the Go reference gets for free.
- ``interleave``: a deterministic interleaving explorer -- a controlled
  scheduler with virtual locks that exhaustively (or seeded-randomly)
  permutes thread schedules over the prepare/unprepare pipeline and
  asserts checkpoint consistency after every one (the targeted
  ``-race`` analog).
- ``statemachine``: the declarative model of legal checkpoint claim
  transitions plus the runtime validator CheckpointManager enforces on
  every group-committed mutation -- and the static crash-closure pass
  (``crash_closure_all``) proving every on-disk state reachable across
  a fault seam has a resume path.
- ``callgraph``: the project-wide call graph the interprocedural lint
  rules (TPUDRA016-018) resolve cross-module edges against.
- ``modelcheck``: the multi-actor protocol model checker -- a modeled
  apiserver with real resourceVersion semantics under the controlled
  scheduler, exploring {2 schedulers, node plugin, recovery controller}
  interleavings (``python -m ...pkg.analysis.modelcheck --smoke``).

Run the linter: ``python -m k8s_dra_driver_gpu_tpu.pkg.analysis`` (or
``make lint-analysis``). See docs/analysis.md.

Only the (dependency-free) state-machine model is re-exported here:
``kubeletplugin/checkpoint.py`` imports through this package on the
PRODUCTION path, so the dev-tooling modules (``lint``, ``interleave``,
``callgraph``, ``modelcheck``) must be imported explicitly by their
consumers -- an import-time bug in the linter must never be able to
take down a node plugin.
"""

from __future__ import annotations

from .statemachine import (  # noqa: F401
    CheckpointTransitionError,
    SINGLE_PHASE_POLICY,
    TWO_PHASE_POLICY,
    TransitionPolicy,
)
