"""Project-wide call graph for the interprocedural lint passes.

The intra-module AST passes in ``lint.py`` stop at a function boundary:
a kube RPC two calls deep under ``_state_lock`` or an API-object
mutation hidden behind a cross-module helper is invisible to them. This
module builds one conservative, import-resolution-based call graph over
every linted source and distills each function to the summaries the
interprocedural rules (TPUDRA016-018) need:

- ``blocking``: the function performs kube I/O (``*.kube.<verb>``) or
  sleeps (``time.sleep``) -- directly, or transitively through resolved
  callees (``blocking_closure``). Each closure entry carries the
  WITNESS PATH of call edges down to the sink, so a finding can say
  exactly which chain smuggled the RPC under the lock.
- ``mutates_params``: parameter names the function mutates in place
  (mutator-method calls, subscript/attribute stores, ``del``) -- the
  laundering half of the informer-object rule: ``helper(cached_obj)``
  is as much a mutation as ``cached_obj["spec"] = ...`` when helper
  writes through its parameter.

Resolution is deliberately conservative (no type inference): bare names
resolve to same-module functions then from-imports; ``self.m(...)`` to
methods of classes in the same module; ``mod.f(...)`` through module
imports. Unresolvable calls contribute nothing -- the rules under-report
rather than guess (the lint suite pins both directions).

Dev tooling: imported by ``lint.py`` only -- never from production
modules (same isolation rule as ``interleave``/``modelcheck``).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

_KUBE_VERBS = {"get", "list", "patch", "create", "delete", "update",
               "watch"}


def _attr_chain(node: ast.AST) -> list[str]:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


@dataclass
class CallSite:
    """One syntactic call inside a function body, pre-resolution."""
    spelling: str          # "helper" | "self.m" | "mod.f" (<=2 segments)
    line: int


@dataclass
class FunctionNode:
    qualname: str          # "pkg/scheduler.py::Scheduler._commit_allocation"
    rel: str               # module path, '/'-separated, fingerprint-stable
    name: str
    cls: str | None
    lineno: int
    params: list[str] = field(default_factory=list)
    calls: list[CallSite] = field(default_factory=list)
    #: direct blocking sink, e.g. ("kube", "self.kube.patch", 123) or
    #: ("sleep", "time.sleep", 45); None when the body has none.
    sink: tuple[str, str, int] | None = None
    #: parameter names written through in place (excl. ``self``).
    mutates_params: set[str] = field(default_factory=set)


class _FunctionScanner(ast.NodeVisitor):
    """Collect per-function call sites + summaries for one module."""

    def __init__(self, rel: str, graph: "CallGraph"):
        self.rel = rel
        self.graph = graph
        self._cls: list[str] = []
        self._fn: list[FunctionNode] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._cls.append(node.name)
        self.generic_visit(node)
        self._cls.pop()

    def _visit_func(self, node) -> None:
        cls = self._cls[-1] if self._cls else None
        qual = f"{self.rel}::" + (f"{cls}.{node.name}" if cls
                                  else node.name)
        params = [a.arg for a in node.args.args + node.args.kwonlyargs
                  if a.arg != "self"]
        fn = FunctionNode(qualname=qual, rel=self.rel, name=node.name,
                          cls=cls, lineno=node.lineno, params=params)
        # Nested defs attribute their calls to the ENCLOSING function:
        # the closure runs (at the latest) while the outer frame's
        # locks may be held, and the laundering rules care about the
        # outer call site anyway.
        if self._fn:
            fn = self._fn[-1]
            self._fn.append(fn)
            self.generic_visit(node)
            self._fn.pop()
            return
        self.graph.add(fn)
        self._fn.append(fn)
        self.generic_visit(node)
        self._fn.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- summaries ------------------------------------------------------------

    def _param_root(self, node: ast.AST) -> str | None:
        fn = self._fn[-1] if self._fn else None
        if fn is None:
            return None
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        if isinstance(node, ast.Name) and node.id in fn.params:
            return node.id
        return None

    _MUTATORS = {"append", "extend", "insert", "remove", "pop",
                 "popitem", "clear", "update", "setdefault", "sort",
                 "reverse", "add", "discard"}

    def visit_Call(self, node: ast.Call) -> None:
        fn = self._fn[-1] if self._fn else None
        func = node.func
        if fn is not None:
            chain = _attr_chain(func)
            # Blocking sinks.
            if chain == ["time", "sleep"] and fn.sink is None:
                fn.sink = ("sleep", "time.sleep", node.lineno)
            elif isinstance(func, ast.Attribute) and \
                    func.attr in _KUBE_VERBS and len(chain) >= 2 and \
                    chain[-2] == "kube" and fn.sink is None:
                fn.sink = ("kube", ".".join(chain), node.lineno)
            # Mutator method through a parameter.
            if isinstance(func, ast.Attribute) and \
                    func.attr in self._MUTATORS:
                root = self._param_root(func.value)
                if root is not None:
                    fn.mutates_params.add(root)
            # Call-site spellings the resolver understands.
            if isinstance(func, ast.Name):
                fn.calls.append(CallSite(func.id, node.lineno))
            elif isinstance(func, ast.Attribute) and \
                    isinstance(func.value, ast.Name):
                fn.calls.append(CallSite(
                    f"{func.value.id}.{func.attr}", node.lineno))
        self.generic_visit(node)

    def _mut_store(self, target: ast.AST) -> None:
        fn = self._fn[-1] if self._fn else None
        if fn is None:
            return
        if isinstance(target, (ast.Subscript, ast.Attribute)):
            root = self._param_root(target.value)
            if root is not None:
                fn.mutates_params.add(root)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._mut_store(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._mut_store(node.target)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._mut_store(t)
        self.generic_visit(node)


class CallGraph:
    """Resolved project call graph + transitive blocking closure."""

    def __init__(self):
        self.nodes: dict[str, FunctionNode] = {}
        # rel -> {func name -> qualname} (module-level functions)
        self.module_funcs: dict[str, dict[str, str]] = {}
        # rel -> {class -> {method -> qualname}}
        self.module_classes: dict[str, dict[str, dict[str, str]]] = {}
        # rel -> {local alias -> ("func", module, name) | ("mod", module)}
        self.imports: dict[str, dict[str, tuple]] = {}
        # module dotted-tail -> rel (resolution of `from .x import y`)
        self._mod_rels: dict[str, str] = {}
        self._closure: dict[str, tuple | None] | None = None

    # -- construction ---------------------------------------------------------

    @classmethod
    def build(cls, sources: dict[str, str]) -> "CallGraph":
        """``sources``: rel path ('/'-separated) -> source text. Files
        that fail to parse are skipped (TPUDRA000 reports them)."""
        graph = cls()
        for rel, source in sorted(sources.items()):
            try:
                tree = ast.parse(source, filename=rel)
            except SyntaxError:
                continue
            graph._index_module(rel, tree)
        for rel, source in sorted(sources.items()):
            try:
                tree = ast.parse(source, filename=rel)
            except SyntaxError:
                continue
            _FunctionScanner(rel, graph).visit(tree)
        return graph

    def _index_module(self, rel: str, tree: ast.Module) -> None:
        mod_name = os.path.splitext(rel.split("/")[-1])[0]
        self._mod_rels.setdefault(mod_name, rel)
        imports = self.imports.setdefault(rel, {})
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                mod = (node.module or "").split(".")[-1]
                for alias in node.names:
                    local = alias.asname or alias.name
                    if mod:
                        imports[local] = ("func", mod, alias.name)
                    else:  # `from . import sibling`
                        imports[local] = ("mod", alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    imports.setdefault(
                        local, ("mod", alias.name.split(".")[-1]))

    def add(self, fn: FunctionNode) -> None:
        self.nodes[fn.qualname] = fn
        self._closure = None
        if fn.cls is None:
            self.module_funcs.setdefault(fn.rel, {})[fn.name] = \
                fn.qualname
        else:
            self.module_classes.setdefault(fn.rel, {}).setdefault(
                fn.cls, {})[fn.name] = fn.qualname

    # -- resolution -----------------------------------------------------------

    def resolve(self, caller: FunctionNode,
                spelling: str) -> list[str]:
        """Qualnames a call spelling may reach from ``caller``. Empty
        when unresolvable (the rules then stay silent)."""
        rel = caller.rel
        if "." not in spelling:
            # Bare name: same-module function wins; else a from-import.
            local = self.module_funcs.get(rel, {}).get(spelling)
            if local is not None:
                return [local]
            imp = self.imports.get(rel, {}).get(spelling)
            if imp is not None and imp[0] == "func":
                target_rel = self._mod_rels.get(imp[1])
                if target_rel is not None:
                    qn = self.module_funcs.get(
                        target_rel, {}).get(imp[2])
                    return [qn] if qn is not None else []
            return []
        base, _, meth = spelling.partition(".")
        if base == "self":
            # Method on the caller's own class (same module); falls
            # back to every same-module class -- helpers often live on
            # a sibling mixin.
            classes = self.module_classes.get(rel, {})
            if caller.cls is not None:
                qn = classes.get(caller.cls, {}).get(meth)
                if qn is not None:
                    return [qn]
            return sorted(
                m[meth] for m in classes.values() if meth in m)
        imp = self.imports.get(rel, {}).get(base)
        if imp is not None and imp[0] == "mod":
            target_rel = self._mod_rels.get(imp[1])
            if target_rel is not None:
                qn = self.module_funcs.get(target_rel, {}).get(meth)
                return [qn] if qn is not None else []
        return []

    # -- transitive blocking closure ------------------------------------------

    def blocking_closure(self) -> dict[str, tuple]:
        """qualname -> (kind, sink_label, sink_line, path) for every
        function that blocks directly or transitively. ``path`` is the
        qualname chain from the function down to (and including) the
        one holding the sink -- the witness edge list TPUDRA017 prints.
        """
        if self._closure is not None:
            return {q: e for q, e in self._closure.items()
                    if e is not None}
        memo: dict[str, tuple | None] = {}

        def visit(qual: str, stack: set[str]) -> tuple | None:
            if qual in memo:
                return memo[qual]
            if qual in stack:
                return None  # recursion: judged by the outer frame
            fn = self.nodes.get(qual)
            if fn is None:
                return None
            if fn.sink is not None:
                kind, label, line = fn.sink
                memo[qual] = (kind, label, line, [qual])
                return memo[qual]
            stack.add(qual)
            found: tuple | None = None
            for site in fn.calls:
                for callee in self.resolve(fn, site.spelling):
                    sub = visit(callee, stack)
                    if sub is not None:
                        kind, label, line, path = sub
                        found = (kind, label, line, [qual] + path)
                        break
                if found is not None:
                    break
            stack.discard(qual)
            memo[qual] = found
            return found

        for qual in sorted(self.nodes):
            visit(qual, set())
        self._closure = memo
        return {q: e for q, e in memo.items() if e is not None}

    def mutating_callees(self, caller: FunctionNode,
                         spelling: str) -> list[FunctionNode]:
        """Resolved callees of ``spelling`` that mutate at least one
        parameter in place (TPUDRA016 raw material)."""
        out = []
        for qual in self.resolve(caller, spelling):
            fn = self.nodes.get(qual)
            if fn is not None and fn.mutates_params:
                out.append(fn)
        return out


def render_edge(path: list[str], sink_label: str,
                sink_line: int | None = None) -> str:
    """Human/CI-readable witness: ``a -> b -> c [kube.patch@L12]``."""
    chain = " -> ".join(path)
    at = f"@L{sink_line}" if sink_line else ""
    return f"{chain} [{sink_label}{at}]"
