"""Shared infrastructure packages (reference: pkg/ and internal/)."""

from __future__ import annotations

import json
import logging
import os


def json_copy(obj):
    """Deep copy of a JSON-shaped API object.

    THE sanctioned way to take a mutable copy of anything read from a
    kube client, an informer cache, or a watch event before changing it
    (the client-go "never mutate cache objects" rule; enforced by lint
    rule TPUDRA006, pkg/analysis/lint.py)."""
    return json.loads(json.dumps(obj))


def positive_float_env(var: str, default: float, floor: float) -> float:
    """Defensive operator-knob parse: a bad value must never crash a
    binary at import, and a non-positive (or NaN) value would busy-spin
    whatever loop waits on it -- clamp to ``floor`` instead."""
    raw = os.environ.get(var, "")
    try:
        val = float(raw)
    except ValueError:
        if raw:
            logging.getLogger(__name__).warning(
                "ignoring non-numeric %s=%r", var, raw)
        return default
    if not (val > 0):  # NaN compares False too
        logging.getLogger(__name__).warning(
            "clamping %s=%s to %s", var, raw, floor)
        return floor
    return val
