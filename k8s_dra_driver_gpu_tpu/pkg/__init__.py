"""Shared infrastructure packages (reference: pkg/ and internal/)."""
