"""Per-segment wall-time instrumentation for the prepare path.

Reference: the t_prep_* klog V6/V7 segments (cmd/gpu-kubelet-plugin/
driver.go:394-404, device_state.go:229-334, nvlib.go:860-930,
cdi.go:306) -- fine-grained timings of lock acquisition, checkpoint
reads/writes, device creation, and CDI spec writes, logged per claim so
field latency regressions are attributable to a segment.
"""

from __future__ import annotations

import logging
import os
import time
from contextlib import contextmanager

from . import faults

logger = logging.getLogger(__name__)

# Fault-injection seams (robustness tests; the bats-suite kill-9 sweep
# analog, reference test_gpu_robustness.bats). Both act at the START of
# the named segment and only when the env var is set:
#   TPU_DRA_CRASH_AT_SEGMENT=<name>  -> os._exit(86)  (SIGKILL analog)
#   TPU_DRA_STALL_AT_SEGMENT=<name> [TPU_DRA_STALL_SECONDS=N] -> sleep
# The pkg/faults registry supersedes both for new tests: every segment
# is also the fault point "segment:<name>" (error/crash/latency modes,
# probability + count, seeded schedules -- see docs/operations.md).
ENV_CRASH_AT = "TPU_DRA_CRASH_AT_SEGMENT"
ENV_STALL_AT = "TPU_DRA_STALL_AT_SEGMENT"
ENV_STALL_SECONDS = "TPU_DRA_STALL_SECONDS"


class SegmentTimer:
    """Collects named wall-time segments for one operation."""

    def __init__(self, operation: str, key: str = ""):
        self.operation = operation
        self.key = key
        self.segments: dict[str, float] = {}
        self._start = time.monotonic()

    @contextmanager
    def segment(self, name: str):
        if os.environ.get(ENV_CRASH_AT) == name:
            logger.warning("fault injection: crashing at segment %s", name)
            os._exit(86)
        if os.environ.get(ENV_STALL_AT) == name:
            time.sleep(float(os.environ.get(ENV_STALL_SECONDS, "5")))
        faults.fault_point(f"segment:{name}")
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.segments[name] = self.segments.get(name, 0.0) + (
                time.monotonic() - t0
            )

    def done(self) -> float:
        """Log the segment breakdown; returns total seconds."""
        total = time.monotonic() - self._start
        if logger.isEnabledFor(logging.DEBUG):
            parts = " ".join(
                f"t_{name}={dt * 1e3:.2f}ms"
                for name, dt in sorted(self.segments.items())
            )
            logger.debug(
                "%s %s total=%.2fms %s",
                self.operation, self.key, total * 1e3, parts,
            )
        return total
