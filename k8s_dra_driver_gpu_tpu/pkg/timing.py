"""Per-segment wall-time instrumentation for the prepare path.

Reference: the t_prep_* klog V6/V7 segments (cmd/gpu-kubelet-plugin/
driver.go:394-404, device_state.go:229-334, nvlib.go:860-930,
cdi.go:306) -- fine-grained timings of lock acquisition, checkpoint
reads/writes, device creation, and CDI spec writes, logged per claim so
field latency regressions are attributable to a segment.

Tracing integration (pkg/tracing.py): a SegmentTimer optionally parents
its operation under a remote span context -- the scheduler's commit
span, carried by the claim's traceparent annotation -- and every
``segment()`` becomes a child span, so the same instants that feed the
klog breakdown and the prepare-segment histogram also appear in the
cross-binary trace. Logging and the fault-injection seams are
byte-for-byte the historical behavior: the seams fire BEFORE any span
exists, so a crash-at-segment never exports a half-open span.
"""

from __future__ import annotations

import logging
import os
import time
from contextlib import contextmanager

from . import faults, tracing

logger = logging.getLogger(__name__)

# Fault-injection seams (robustness tests; the bats-suite kill-9 sweep
# analog, reference test_gpu_robustness.bats). Both act at the START of
# the named segment and only when the env var is set:
#   TPU_DRA_CRASH_AT_SEGMENT=<name>  -> os._exit(86)  (SIGKILL analog)
#   TPU_DRA_STALL_AT_SEGMENT=<name> [TPU_DRA_STALL_SECONDS=N] -> sleep
# The pkg/faults registry supersedes both for new tests: every segment
# is also the fault point "segment:<name>" (error/crash/latency modes,
# probability + count, seeded schedules -- see docs/operations.md).
ENV_CRASH_AT = "TPU_DRA_CRASH_AT_SEGMENT"
ENV_STALL_AT = "TPU_DRA_STALL_AT_SEGMENT"
ENV_STALL_SECONDS = "TPU_DRA_STALL_SECONDS"


class SegmentTimer:
    """Collects named wall-time segments for one operation.

    ``parent`` (a pkg/tracing Span or SpanContext, typically extracted
    from the claim's traceparent annotation) makes the whole operation
    a child span of a remote trace; with no parent the operation starts
    its own trace (sampling-gated). The operation span is exported at
    :meth:`done` -- tracing sanctions this module's ``start_span``
    (lint TPUDRA012) because the timer's lifetime is not lexical."""

    def __init__(self, operation: str, key: str = "", parent=None):
        self.operation = operation
        self.key = key
        self.segments: dict[str, float] = {}
        self._start = time.monotonic()
        attrs = {"claim_uid": key} if key else None
        self._span = tracing.start_span(operation, parent=parent,
                                        attrs=attrs)

    @property
    def trace_id(self) -> str:
        """The sampled trace id this operation records under, or ''."""
        return (self._span.context.trace_id
                if self._span.recording else "")

    @property
    def span(self):
        """The operation span (child segment spans parent here)."""
        return self._span

    @contextmanager
    def segment(self, name: str):
        if os.environ.get(ENV_CRASH_AT) == name:
            logger.warning("fault injection: crashing at segment %s", name)
            os._exit(86)
        if os.environ.get(ENV_STALL_AT) == name:
            time.sleep(float(os.environ.get(ENV_STALL_SECONDS, "5")))
        faults.fault_point(f"segment:{name}")
        t0 = time.monotonic()
        try:
            with tracing.span(name, parent=self._span,
                              attrs=({"claim_uid": self.key}
                                     if self.key else None)):
                yield
        finally:
            self.segments[name] = self.segments.get(name, 0.0) + (
                time.monotonic() - t0
            )

    def done(self) -> float:
        """Log the segment breakdown; returns total seconds."""
        total = time.monotonic() - self._start
        if self._span.recording:
            self._span.set_attr("total_ms", round(total * 1e3, 3))
            for name, dt in self.segments.items():
                self._span.set_attr(f"t_{name}_ms", round(dt * 1e3, 3))
        self._span.finish()
        if logger.isEnabledFor(logging.DEBUG):
            parts = " ".join(
                f"t_{name}={dt * 1e3:.2f}ms"
                for name, dt in sorted(self.segments.items())
            )
            logger.debug(
                "%s %s total=%.2fms %s",
                self.operation, self.key, total * 1e3, parts,
            )
        return total
