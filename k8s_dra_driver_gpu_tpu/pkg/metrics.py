"""Prometheus metrics for the DRA drivers.

Reference: pkg/metrics (DRA request duration histograms, in-flight and
error counters, prepared-devices gauge -- dra_requests.go:27-151; the
ComputeDomain cluster-status gauge -- computedomain_cluster.go; HTTP
exposition server -- prometheus_httpserver.go).
"""

from __future__ import annotations

import time
from contextlib import contextmanager

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

from . import fleetstate, flightrecorder, tracing
from .debug import debug_stacks_endpoint
from .httpserver import SimpleHTTPEndpoint

_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60)


def register_build_info(registry: CollectorRegistry,
                        gates=None) -> Gauge:
    """The ``tpu_dra_build_info`` info-gauge every binary exposes:
    value 1, labels carrying the VERSION-file version and the active
    (enabled) feature gates -- so a fleet dashboard can pivot any
    metric by code version / gate set during a rollout. Call once per
    registry (each binary's main; the metrics-hygiene test asserts
    presence and label contract)."""
    from .. import __version__  # noqa: PLC0415
    from .featuregates import (  # noqa: PLC0415
        KNOWN_FEATURES,
        FeatureGateError,
        FeatureGates,
    )

    if gates is None:
        # Default to the SAME source the binary resolves its gates
        # from (FEATURE_GATES env): callers without an explicit gate
        # object must still advertise what is actually active.
        try:
            gates = FeatureGates.from_env()
        except FeatureGateError:
            gates = FeatureGates()
    active = ",".join(sorted(
        name for name in KNOWN_FEATURES if gates.is_enabled(name)))
    g = Gauge(
        "tpu_dra_build_info",
        "Build/version identity (value is always 1; the labels carry "
        "the information).",
        ["version", "feature_gates"],
        registry=registry,
    )
    g.labels(version=__version__, feature_gates=active).set(1)
    return g


class ClaimSLOMetrics:
    """Claim-lifecycle SLO accounting (pkg/tracing.py's metric half).

    One histogram, ``tpu_dra_claim_e2e_seconds``, labeled by lifecycle
    phase so the end-to-end latency a user feels decomposes into WHO
    owes it: ``queued`` (dirty-key enqueue -> sync start, the
    scheduler's backlog), ``fit`` (candidate walk + constraint DFS),
    ``commit`` (atomic reserve), ``patch`` (the allocation kube write),
    ``prepare`` (node-side NodePrepareResources, reported by both
    kubelet plugins), and ``evict`` (recovery-controller eviction to
    re-placement). Observations carry the claim's trace id as an
    OpenMetrics exemplar when one is active, so a histogram outlier
    links straight to its span tree in ``/debug/traces``."""

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.e2e = Histogram(
            "tpu_dra_claim_e2e_seconds",
            "Per-phase claim-lifecycle latency (queued/fit/commit/"
            "patch/prepare on the hot path; evict for recovery), with "
            "trace-id exemplars linking outliers to /debug/traces.",
            ["phase"],
            buckets=_BUCKETS,
            registry=self.registry,
        )
        # labels() is ~4us of dict/validation per call; the phase set
        # is tiny and this sits on the per-allocation hot path.
        self._children: dict = {}

    def observe(self, phase: str, seconds: float,
                trace_id: str = "") -> None:
        h = self._children.get(phase)
        if h is None:
            h = self._children[phase] = self.e2e.labels(phase)
        amount = max(float(seconds), 0.0)
        if trace_id:
            try:
                h.observe(amount, {"trace_id": trace_id[:32]})
                return
            except (TypeError, ValueError):
                pass  # old prometheus_client / oversized exemplar
        h.observe(amount)


class DRARequestMetrics:
    """Per-operation DRA request metrics (reference dra_requests.go)."""

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.duration = Histogram(
            "tpu_dra_request_duration_seconds",
            "Duration of DRA plugin requests by operation.",
            ["operation"],
            buckets=_BUCKETS,
            registry=self.registry,
        )
        self.in_flight = Gauge(
            "tpu_dra_requests_in_flight",
            "Number of DRA plugin requests currently being served.",
            ["operation"],
            registry=self.registry,
        )
        self.errors = Counter(
            "tpu_dra_request_errors_total",
            "Total DRA plugin request errors by operation.",
            ["operation"],
            registry=self.registry,
        )
        self.prepared_devices = Gauge(
            "tpu_dra_prepared_devices",
            "Number of devices currently prepared for claims.",
            registry=self.registry,
        )
        self.device_taints = Gauge(
            "tpu_dra_device_taints",
            "Current DRA device taints by health kind.",
            ["kind"],
            registry=self.registry,
        )
        self.tenancy_agents = Gauge(
            "tpu_dra_tenancy_agents",
            "Supervised multi-tenancy enforcement agents running.",
            registry=self.registry,
        )
        # Per-segment breakdown of the prepare/unprepare pipeline
        # (prep_lock_wait, ckpt_fsync_wait, prep_devices, ...): the
        # observability half of the sharded-lock work -- lock-wait
        # regressions show up here before they move the p99.
        # Publish-diff effectiveness (pkg/sliceutil): slice writes
        # avoided because the live spec already matched by content
        # hash. A health-republish storm that stays write-free shows
        # up here instead of as apiserver load.
        self.slice_publish_skipped = Counter(
            "tpu_dra_slice_publish_skipped_total",
            "ResourceSlice writes skipped by the content-hash publish "
            "diff (unchanged spec, no PUT issued).",
            registry=self.registry,
        )
        self.prepare_segment = Histogram(
            "tpu_dra_prepare_segment_seconds",
            "Wall time of instrumented prepare/unprepare segments "
            "(lock waits, checkpoint fsync waits, device setup).",
            ["operation", "segment"],
            buckets=_BUCKETS,
            registry=self.registry,
        )
        # The node plugin's slice of the claim-lifecycle SLO histogram
        # (phase="prepare"); the scheduler exports the control-plane
        # phases from its own registry (SchedulerMetrics.slo).
        self.slo = ClaimSLOMetrics(registry=self.registry)
        # Per-chip power/thermal/utilization telemetry + anomaly
        # episode counts (the fleet telemetry plane's node half; fed
        # by the health-poll loop through kubeletplugin/driver.py).
        # Labeled families export nothing until a chip reports, so a
        # telemetry-less binary sharing this class pays zero scrape
        # noise.
        self.telemetry = TelemetryMetrics(registry=self.registry)

    def observe_segments(self, operation: str, segments: dict) -> None:
        """DeviceState.segment_observer hook: one histogram sample per
        timed segment of a prepare/unprepare."""
        for name, dt in segments.items():
            self.prepare_segment.labels(operation, name).observe(dt)

    def set_taints(self, taints) -> None:
        """Reconcile the taint gauge from the full current taint list
        (clears kinds that no longer apply)."""
        counts: dict[str, int] = {}
        for t in taints:
            kind = t.key.rsplit("/", 1)[-1]
            counts[kind] = counts.get(kind, 0) + 1
        seen = getattr(self, "_taint_kinds", set())
        for kind in seen - set(counts):
            self.device_taints.labels(kind).set(0)
        for kind, n in counts.items():
            self.device_taints.labels(kind).set(n)
        self._taint_kinds = seen | set(counts)

    @contextmanager
    def observe(self, operation: str):
        self.in_flight.labels(operation).inc()
        start = time.monotonic()
        try:
            yield
        except BaseException:
            self.errors.labels(operation).inc()
            raise
        finally:
            self.duration.labels(operation).observe(time.monotonic() - start)
            self.in_flight.labels(operation).dec()


class ResilienceMetrics:
    """Retry / circuit-breaker / gang-abort / quarantine observability
    (the resilience layer, pkg/retry.py + kubeletplugin/health.py +
    computedomain/plugin/driver.py).

    Every self-healing decision the stack takes under failure shows up
    here: a rising ``retry_total`` is an apiserver (or network) getting
    sick, ``circuit_open_total`` is it being DOWN, ``gang_abort_total``
    is straggler nodes blowing multi-host prepare deadlines, and
    ``quarantine_total`` is chips flapping their way out of the
    schedulable pool."""

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.retries = Counter(
            "tpu_dra_retry_total",
            "Retried kube API attempts by verb (RetryingKubeClient).",
            ["verb"],
            registry=self.registry,
        )
        self.circuit_open = Counter(
            "tpu_dra_circuit_open_total",
            "Times the kube circuit breaker tripped open.",
            registry=self.registry,
        )
        self.gang_aborts = Counter(
            "tpu_dra_gang_abort_total",
            "Gang prepares aborted at the rendezvous deadline (own "
            "node's state unwound, failure reported retriable).",
            registry=self.registry,
        )
        self.quarantines = Counter(
            "tpu_dra_quarantine_total",
            "Chips escalated to NoSchedule quarantine after repeated "
            "non-fatal health events.",
            ["device"],
            registry=self.registry,
        )


class RecoveryMetrics:
    """Permanent-failure recovery observability (pkg/recovery.py +
    kubeletplugin/reconcile.py).

    Two producers share this family: the scheduler-side eviction &
    migration controller (evictions, replacements, deadline failures,
    declared permanent failures) and the per-node reconciliation sweep
    (orphans repaired, cross-layer drift). A healthy fleet shows
    ``permanent_failures_total`` rising only with real hardware events,
    every eviction paired with a ``replaced``/``failed`` retirement,
    ``active_evictions`` returning to zero, and a sweep that finds
    nothing (``reconcile_drift`` at 0) -- persistent drift means some
    layer is leaking state faster than the sweep repairs it."""

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.permanent_failures = Counter(
            "tpu_dra_recovery_permanent_failures_total",
            "Claims declared permanently failed, by failure source "
            "(node = NotReady past deadline / deleted; device = fatal "
            "chip taint; gang = healthy companion of a failed gang "
            "member; sweep = node sweep found devices gone).",
            ["source"],
            registry=self.registry,
        )
        self.evictions = Counter(
            "tpu_dra_recovery_evictions_total",
            "Claim evictions started by the recovery controller "
            "(drain + deallocate of a permanently failed claim).",
            registry=self.registry,
        )
        self.replaced = Counter(
            "tpu_dra_recovery_replaced_total",
            "Evicted claims that converged to a fresh allocation on "
            "surviving capacity.",
            registry=self.registry,
        )
        self.failed = Counter(
            "tpu_dra_recovery_failed_total",
            "Evicted claims that blew the per-claim recovery deadline "
            "and were retired as cleanly Failed (no allocation, no "
            "in-flight eviction record).",
            registry=self.registry,
        )
        self.active_evictions = Gauge(
            "tpu_dra_recovery_active_evictions",
            "Eviction records currently in flight (bounded by "
            "TPU_DRA_RECOVERY_MAX_CONCURRENT).",
            registry=self.registry,
        )
        self.orphans_repaired = Counter(
            "tpu_dra_recovery_orphans_repaired_total",
            "Orphaned node-local artifacts repaired by the reconcile "
            "sweep, by kind (carveout, cdi_spec, lease, stale_claim, "
            "cd_stale_claim, cd_cdi_spec, slice).",
            ["kind"],
            registry=self.registry,
        )
        self.reconcile_drift = Gauge(
            "tpu_dra_recovery_reconcile_drift",
            "Cross-layer divergences observed by the LAST reconcile "
            "sweep, by kind (devices_gone counts claims whose "
            "checkpointed devices no longer exist on the host).",
            ["kind"],
            registry=self.registry,
        )


class PlacementMetrics:
    """Topology-aware placement observability (pkg/topology).

    ``pool`` labels carry the resource-pool identity (scheduler) or
    the ``<grid>/<policy>`` identity (placement simulator). The frag
    gauge is THE churn-health signal: rising values mean the free
    space is shredding and large claims will start starving."""

    # Max-hop distances are tiny integers; a torus diameter above 16
    # does not exist on shipping slices.
    _HOP_BUCKETS = (0, 1, 2, 3, 4, 6, 8, 12, 16)

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.frag_score = Gauge(
            "tpu_dra_placement_frag_score",
            "Fragmentation of a pool's free chips: 1 - largest "
            "allocatable sub-torus / free chips (0 = one perfect "
            "contiguous block).",
            ["pool"],
            registry=self.registry,
        )
        self.largest_shape = Gauge(
            "tpu_dra_placement_largest_free_shape_chips",
            "Chips in the largest sub-torus shape still allocatable "
            "from a pool's free chips.",
            ["pool"],
            registry=self.registry,
        )
        self.compactness = Histogram(
            "tpu_dra_placement_compactness",
            "Max ICI hop distance inside each allocated device set "
            "(0 = single chip; lower = tighter collective).",
            ["pool"],
            buckets=self._HOP_BUCKETS,
            registry=self.registry,
        )


class WorkQueueMetrics:
    """Keyed-workqueue observability (pkg/workqueue), the metrics sink
    the queue calls through its duck-typed ``metrics`` hook.

    ``shard`` labels carry the worker index that owns the shard (the
    queue routes every key's shard to exactly one worker), so a single
    hot shard shows up as one deep gauge while its siblings sit at
    zero. ``wait_seconds`` measures enqueue-to-run latency INCLUDING
    any retry or hot-key backoff the item waited out -- a healthy
    scheduler queue stays in the low-millisecond buckets.
    ``hot_backoff_total`` counts fairness escalations: a key re-dirtied
    in a tight loop being throttled so cold keys on its worker keep
    draining."""

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.depth = Gauge(
            "tpu_dra_workqueue_depth",
            "Keys queued per workqueue shard (worker index).",
            ["shard"],
            registry=self.registry,
        )
        self.wait = Histogram(
            "tpu_dra_workqueue_wait_seconds",
            "Queue latency from enqueue to callback start, including "
            "retry/hot-key backoff.",
            buckets=_BUCKETS,
            registry=self.registry,
        )
        self.retries = Counter(
            "tpu_dra_workqueue_retries_total",
            "Callbacks re-enqueued with backoff after an error.",
            registry=self.registry,
        )
        self.drops = Counter(
            "tpu_dra_workqueue_drops_total",
            "Keys dropped (PermanentError or retry budget exhausted).",
            registry=self.registry,
        )
        self.hot_backoffs = Counter(
            "tpu_dra_workqueue_hot_backoff_total",
            "Fairness escalations applied to keys re-dirtied in a "
            "tight loop (pkg/workqueue hot-key damping).",
            registry=self.registry,
        )
        self.steals = Counter(
            "tpu_dra_workqueue_steals_total",
            "Ready keys stolen by idle workers from a backlogged "
            "sibling's heap (pkg/workqueue work stealing); a rising "
            "rate means one shard is hot enough to flood its owner.",
            registry=self.registry,
        )

    # -- the duck-typed sink pkg/workqueue calls ------------------------------

    def set_depth(self, shard: str, n: int) -> None:
        self.depth.labels(shard).set(n)

    def observe_wait(self, seconds: float) -> None:
        self.wait.observe(max(seconds, 0.0))

    def inc_retry(self) -> None:
        self.retries.inc()

    def inc_drop(self) -> None:
        self.drops.inc()

    def inc_hot_backoff(self) -> None:
        self.hot_backoffs.inc()

    def inc_steal(self, n: int = 1) -> None:
        self.steals.inc(n)


class SchedulerMetrics:
    """Event-driven scheduler observability (pkg/scheduler +
    pkg/schedcache + pkg/informer).

    The headline health signal is the PAIR (sync_seconds by mode,
    dirty_queue_depth): a healthy event-driven control plane shows
    cheap ``incremental`` samples dominating, rare ``full`` safety
    resyncs, and a dirty queue that returns to zero between bursts.
    ``informer_relist_total`` rising means the cheap incremental event
    path is being bypassed (watch gaps, kind-less fake events);
    ``slice_publish_skipped_total`` counts the writes the content-hash
    publish diff avoided (pkg/sliceutil) for publishers wired to THIS
    registry -- node drivers run in their own processes and export
    their own copy via DRARequestMetrics, so in the scheduler binary
    this reads 0 unless a scheduler-side publisher exists; dashboards
    should aggregate the metric name across jobs."""

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.sync_seconds = Histogram(
            "tpu_dra_sched_sync_seconds",
            "Scheduler sync work duration by mode (full resync pass "
            "vs. one incremental dirty-key drain).",
            ["mode"],
            buckets=_BUCKETS,
            registry=self.registry,
        )
        self.dirty_depth = Gauge(
            "tpu_dra_sched_dirty_queue_depth",
            "Dirty keys currently queued for incremental sync.",
            registry=self.registry,
        )
        self.publish_skipped = Counter(
            "tpu_dra_slice_publish_skipped_total",
            "ResourceSlice writes skipped because the desired spec "
            "matched the live spec by canonical content hash.",
            registry=self.registry,
        )
        self.informer_relists = Counter(
            "tpu_dra_informer_relist_total",
            "Full informer relists by resource (the expensive fallback "
            "path; incremental watch events do not count).",
            ["resource"],
            registry=self.registry,
        )
        self.snapshot_build = Histogram(
            "tpu_dra_sched_snapshot_build_seconds",
            "Wall time to (re)build the indexed inventory snapshot "
            "from the published ResourceSlices (pkg/schedcache); one "
            "sample per actual rebuild, cache hits cost nothing.",
            buckets=_BUCKETS,
            registry=self.registry,
        )
        self.snapshot_delta = Histogram(
            "tpu_dra_sched_snapshot_delta_seconds",
            "Per-pool incremental sub-snapshot rebuild time on the "
            "delta path (pkg/schedcache PoolSnapshot): one sample per "
            "pool actually re-projected by a slice event; untouched "
            "pools merge by identity and cost nothing. A healthy "
            "10k-node fleet shows this replacing snapshot_build "
            "entirely outside full resyncs.",
            ["pool"],
            buckets=_BUCKETS,
            registry=self.registry,
        )
        self.relist_backoff = Histogram(
            "tpu_dra_informer_relist_backoff_seconds",
            "Jittered backoff the relist coordinator applied before "
            "an informer's full relist (pkg/informer "
            "RelistCoordinator): repeated relists of one resource "
            "inside the quiet window back off exponentially so a "
            "restart storm drains without thundering-herding the "
            "apiserver. Quiet resources relist with zero delay and "
            "record nothing here.",
            ["resource"],
            buckets=_BUCKETS,
            registry=self.registry,
        )
        self.domain_spilled = Counter(
            "tpu_dra_sched_domain_spilled_total",
            "Claims re-homed by cross-domain spillover: a claim "
            "pinned to an exhausted scheduling domain was annotated "
            "over to a sibling domain (migration-cost ranked) instead "
            "of pending forever.",
            ["from_domain", "to_domain"],
            registry=self.registry,
        )
        self.domain_exhausted = Counter(
            "tpu_dra_sched_domain_exhausted_total",
            "Allocation attempts for domain-pinned claims that found "
            "no fit inside their scheduling domain's pools (the claim "
            "gets a DomainExhausted condition + Warning Event instead "
            "of waiting silently).",
            ["domain"],
            registry=self.registry,
        )
        self.commit_conflicts = Counter(
            "tpu_dra_sched_commit_conflicts_total",
            "Optimistic allocation commits rejected at reserve time "
            "(another worker took a device/counter between fit and "
            "commit); each conflict re-fits against fresh state.",
            registry=self.registry,
        )
        # Per-shard queue depth / wait / retry observability for the
        # scheduler's sharded sync queue (pkg/workqueue).
        self.workqueue = WorkQueueMetrics(registry=self.registry)
        # Claim-lifecycle SLO phases owned by the control plane
        # (queued/fit/commit/patch; the recovery controller's evict
        # phase shares this instance via attach_recovery).
        self.slo = ClaimSLOMetrics(registry=self.registry)


class PartitionMetrics:
    """Partition-engine observability (pkg/partition/engine.py).

    A healthy serving node shows ``partitions_active`` tracking tenant
    load (carve-outs realized on demand, reaped when idle) and the
    create/destroy counters moving together; ``creates`` racing ahead
    of ``destroys`` without ``partitions_active`` rising means crashed
    teardowns are being resumed (check the node plugin logs)."""

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.partitions_active = Gauge(
            "tpu_dra_partitions_active",
            "Partition carve-outs currently realized (PartitionReady "
            "records) on this node.",
            registry=self.registry,
        )
        self.creates = Counter(
            "tpu_dra_partition_creates_total",
            "Partition carve-outs created (first tenant attach).",
            registry=self.registry,
        )
        self.destroys = Counter(
            "tpu_dra_partition_destroys_total",
            "Partition carve-outs destroyed (last tenant detach, idle "
            "reap, or crash-resumed teardown).",
            registry=self.registry,
        )
        # Predictive pre-warming (pkg/partition/engine.set_prewarm,
        # fed by the autoscale forecaster's CRD hint): created counts
        # carve-outs realized AHEAD of demand, hit counts first
        # attaches that found a warm carve-out (skipping the
        # partition.create fsyncs on the claim path), reaped counts
        # warm-but-never-attached carve-outs returned by the idle
        # sweep after the forecast decayed. hit/created is the
        # forecaster's precision.
        self.prewarm_created = Counter(
            "tpu_dra_prewarm_created_total",
            "Partition carve-outs pre-realized ahead of forecast "
            "demand.",
            registry=self.registry,
        )
        self.prewarm_hits = Counter(
            "tpu_dra_prewarm_hit_total",
            "Tenant attaches that landed on a pre-warmed carve-out "
            "(no partition.create on the claim path).",
            registry=self.registry,
        )
        self.prewarm_reaped = Counter(
            "tpu_dra_prewarm_reaped_total",
            "Pre-warmed carve-outs reaped un-attached after the "
            "forecast decayed.",
            registry=self.registry,
        )

    # -- the duck-typed sink pkg/partition/engine.py calls --------------------

    def inc_create(self) -> None:
        self.creates.inc()

    def inc_destroy(self) -> None:
        self.destroys.inc()

    def set_active(self, n: int) -> None:
        self.partitions_active.set(n)

    def inc_prewarm_created(self) -> None:
        self.prewarm_created.inc()

    def inc_prewarm_hit(self) -> None:
        self.prewarm_hits.inc()

    def inc_prewarm_reaped(self) -> None:
        self.prewarm_reaped.inc()


class TelemetryMetrics:
    """Per-chip telemetry exposition (the node collector's metric
    half; kubeletplugin/health.py feeds it on the health-poll cadence
    from the ``tpulib.chip_telemetry`` seam, kubeletplugin/driver.py
    wires it onto the plugin registry).

    The gauges are instantaneous per-chip signals; ``ici_link_errors``
    re-exports tpulib's CUMULATIVE counter as deltas so Prometheus
    ``rate()`` works across plugin restarts. ``anomaly_total`` counts
    detection EPISODES (pkg/anomaly.py rising edges), not per-poll
    presence -- a sustained thermal drift is one anomaly, not one per
    5s."""

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.power = Gauge(
            "tpu_dra_chip_power_watts",
            "Instantaneous per-chip power draw (tpulib telemetry).",
            ["chip"],
            registry=self.registry,
        )
        self.temp = Gauge(
            "tpu_dra_chip_temp_celsius",
            "Per-chip die temperature (tpulib telemetry).",
            ["chip"],
            registry=self.registry,
        )
        self.hbm_used = Gauge(
            "tpu_dra_chip_hbm_used_bytes",
            "Per-chip HBM bytes in use (tpulib telemetry).",
            ["chip"],
            registry=self.registry,
        )
        self.duty = Gauge(
            "tpu_dra_chip_duty_cycle",
            "Per-chip TensorCore duty cycle, 0.0-1.0 (tpulib "
            "telemetry).",
            ["chip"],
            registry=self.registry,
        )
        self.ici_errors = Counter(
            "tpu_dra_chip_ici_link_errors_total",
            "ICI link errors observed per chip (delta of tpulib's "
            "cumulative counter).",
            ["chip"],
            registry=self.registry,
        )
        self.anomalies = Counter(
            "tpu_dra_anomaly_total",
            "Telemetry anomaly episodes detected, by kind "
            "(thermal_drift, power_cap_throttle, duty_cycle_straggler, "
            "ici_link_error_burst; pkg/anomaly.py).",
            ["kind"],
            registry=self.registry,
        )
        self._ici_last: dict[str, int] = {}

    # -- the sinks kubeletplugin/{health,driver}.py call ----------------------

    def observe_sample(self, sample) -> None:
        """One ChipTelemetry sample -> gauge updates + the ICI error
        delta."""
        chip = str(sample.chip)
        self.power.labels(chip).set(float(sample.power_watts))
        self.temp.labels(chip).set(float(sample.temp_celsius))
        self.hbm_used.labels(chip).set(int(sample.hbm_used_bytes))
        self.duty.labels(chip).set(float(sample.duty_cycle))
        cum = int(sample.ici_link_errors)
        last = self._ici_last.get(chip)
        self._ici_last[chip] = cum
        if last is not None and cum > last:
            self.ici_errors.labels(chip).inc(cum - last)

    def prune_absent(self, present_chips) -> None:
        """Remove gauge children for chips absent from the current
        sample set: a dead sensor must read as NO data, not a
        frozen-but-plausible last value summed into dashboards
        (mirrors the slice-attribute replace semantics)."""
        present = {str(c) for c in present_chips}
        for chip in set(self._ici_last) - present:
            for gauge in (self.power, self.temp, self.hbm_used,
                          self.duty):
                try:
                    gauge.remove(chip)
                except KeyError:
                    pass
            # The error counter keeps its history (it is a counter),
            # but the delta baseline resets so a returning chip
            # re-baselines instead of double-counting.
            self._ici_last.pop(chip, None)

    def inc_anomaly(self, kind: str) -> None:
        self.anomalies.labels(kind).inc()


class FleetMetrics:
    """Fleet-aggregator exposition (pkg/fleetstate.FleetAggregator's
    duck-typed sink, on the scheduler registry).

    ``pool_utilization`` near 1.0 with ``pending_claims`` above zero is
    the capacity-starvation signal; ``node_power_watts`` /
    ``node_temp_celsius`` are the scheduler-visible per-node power and
    thermal envelope folded from the slice attributes the node plugins
    publish (the 2501.17752 power-as-scheduler-signal input). Frag
    history lives in PlacementMetrics + /debug/fleet."""

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.pool_utilization = Gauge(
            "tpu_dra_fleet_pool_utilization",
            "Allocated fraction of a pool's devices (0.0-1.0), from "
            "the scheduler's AllocationState.",
            ["pool"],
            registry=self.registry,
        )
        self.pool_free = Gauge(
            "tpu_dra_fleet_pool_free_devices",
            "Devices currently unallocated in a pool.",
            ["pool"],
            registry=self.registry,
        )
        self.pending = Gauge(
            "tpu_dra_fleet_pending_claims",
            "Claims waiting for capacity (demand the free pools are "
            "not absorbing).",
            registry=self.registry,
        )
        self.node_power = Gauge(
            "tpu_dra_fleet_node_power_watts",
            "Per-node power draw summed from the telemetry slice "
            "attributes the node plugins publish (quantized).",
            ["node"],
            registry=self.registry,
        )
        self.node_temp = Gauge(
            "tpu_dra_fleet_node_temp_celsius",
            "Per-node hottest-chip temperature from the telemetry "
            "slice attributes (quantized).",
            ["node"],
            registry=self.registry,
        )
        self.power_headroom = Gauge(
            "tpu_dra_fleet_power_headroom_watts",
            "Per-pool power headroom: summed node power caps "
            "(powerCapWatts attributes / TPU_DRA_POWER_CAP_W) minus "
            "the summed telemetry draw, with dropped power samples "
            "carried for TPU_DRA_POWER_SAMPLE_TTL_S. Absent when no "
            "cap is configured (power model off).",
            ["pool"],
            registry=self.registry,
        )
        self.fold_seconds = Histogram(
            "tpu_dra_fleet_fold_seconds",
            "Wall time of one FleetAggregator fold (per-pool "
            "utilization + fragmentation over the whole inventory). "
            "Kept flat by the largest_free_shape memo in "
            "pkg/topology/score.py -- a rising p99 here means the "
            "memo stopped hitting (pool geometry churning every "
            "pass).",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5),
            registry=self.registry,
        )

    # -- the duck-typed sink pkg/fleetstate.py calls --------------------------

    def set_pool(self, pool: str, utilization: float,
                 free: int) -> None:
        self.pool_utilization.labels(pool).set(utilization)
        self.pool_free.labels(pool).set(free)

    def set_pending(self, n: int) -> None:
        self.pending.set(n)

    def set_node(self, node: str, power_w: float, temp_c: float) -> None:
        self.node_power.labels(node).set(power_w)
        self.node_temp.labels(node).set(temp_c)

    def set_pool_power(self, pool: str, headroom_w: float) -> None:
        self.power_headroom.labels(pool).set(headroom_w)

    def remove_pool_power(self, pool: str) -> None:
        """A still-present pool stopped publishing power caps: its
        headroom gauge disappears rather than freezing (the power
        model is off, not at its last value)."""
        try:
            self.power_headroom.remove(pool)
        except KeyError:
            pass

    def remove_pool(self, pool: str) -> None:
        """A pool left the snapshot: its gauges must disappear rather
        than freeze at the last value."""
        for gauge in (self.pool_utilization, self.pool_free,
                      self.power_headroom):
            try:
                gauge.remove(pool)
            except KeyError:
                pass

    def remove_node(self, node: str) -> None:
        for gauge in (self.node_power, self.node_temp):
            try:
                gauge.remove(node)
            except KeyError:
                pass


class DefragMetrics:
    """Active-defragmentation observability (pkg/defrag.py, on the
    scheduler registry).

    A healthy controller shows ``plans_total`` rising only when churn
    has genuinely shredded a pool (the hysteresis proof: a quiet fleet
    shows zero), every planned move retiring through ``moves_total``
    (``aborted_total`` staying flat), ``frag_recovered_chips_total``
    tracking the largest-free-shape growth each completed plan bought,
    and move latency (plan -> re-placement) bounded by the scheduler's
    re-placement path. ``active_moves`` returning to zero after every
    window is the no-stuck-claims invariant."""

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.plans = Counter(
            "tpu_dra_defrag_plans_total",
            "Defrag plan windows started (a triggered pool with a "
            "feasible re-pack admitted for execution).",
            registry=self.registry,
        )
        self.moves = Counter(
            "tpu_dra_defrag_moves_total",
            "Claim migrations completed by the defrag controller "
            "(drain -> deallocate -> re-placement retired).",
            registry=self.registry,
        )
        self.frag_recovered = Counter(
            "tpu_dra_defrag_frag_recovered_chips_total",
            "Chips of largest-free-sub-torus growth recovered by "
            "completed defrag plans (chips_after - chips_before, "
            "summed per plan window).",
            registry=self.registry,
        )
        self.aborted = Counter(
            "tpu_dra_defrag_aborted_total",
            "Defrag moves abandoned (move deadline exceeded, claim "
            "deleted mid-move, or pool healed mid-plan).",
            registry=self.registry,
        )
        self.active_moves = Gauge(
            "tpu_dra_defrag_active_moves",
            "Defrag move records currently in flight (bounded by "
            "TPU_DRA_DEFRAG_MAX_CONCURRENT).",
            registry=self.registry,
        )
        self.move_seconds = Histogram(
            "tpu_dra_defrag_move_seconds",
            "End-to-end latency of one completed defrag move: plan "
            "record written -> claim re-placed on surviving capacity.",
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                     60.0, 120.0, 300.0),
            registry=self.registry,
        )


class MigrationMetrics:
    """Cooperative-migration observability (pkg/migration.py, on the
    scheduler registry).

    A healthy controller shows every ``plans_total`` retiring through
    ``coop_moves_total`` with ``fallbacks_total`` flat -- a rising
    fallback rate means workloads stopped honoring the ack contract
    (read the ``reason`` label: ack-timeout means the ack window is
    undersized for real checkpoint time, checkpoint-failed means the
    workload's own save path is broken, destination-lost means the
    fleet is losing capacity mid-handshake). ``ack_seconds`` is the
    workload's checkpoint time (size TPU_DRA_MIGRATION_ACK_S from its
    p99); ``switch_seconds`` is the actual downtime (drain ->
    re-placed); ``move_seconds`` the whole handshake. ``active_moves``
    returning to zero after every handshake is the no-stuck-claims
    invariant the chaos suite pins."""

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.plans = Counter(
            "tpu_dra_migration_plans_total",
            "Cooperative move groups planned (destination reserved, "
            "durable records written).",
            registry=self.registry,
        )
        self.coop_moves = Counter(
            "tpu_dra_migration_coop_moves_total",
            "Cooperative migrations completed warm (workload acked "
            "its checkpoint, claim re-placed on the reserved window).",
            registry=self.registry,
        )
        self.fallbacks = Counter(
            "tpu_dra_migration_fallbacks_total",
            "Cooperative moves degraded to the cold eviction path, by "
            "reason (ack-timeout, checkpoint-failed, "
            "destination-lost, deadline). The claim is never stuck: "
            "fallback releases the reservation and drains cold.",
            ["reason"],
            registry=self.registry,
        )
        self.active_moves = Gauge(
            "tpu_dra_migration_active_moves",
            "Migration handshake records currently in flight (bounded "
            "by TPU_DRA_MIGRATION_MAX_CONCURRENT).",
            registry=self.registry,
        )
        self.ack_seconds = Histogram(
            "tpu_dra_migration_ack_seconds",
            "Workload checkpoint time: intent signaled -> ack "
            "annotation observed. Size TPU_DRA_MIGRATION_ACK_S from "
            "this histogram's p99.",
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                     60.0, 120.0, 300.0),
            registry=self.registry,
        )
        self.switch_seconds = Histogram(
            "tpu_dra_migration_switch_seconds",
            "The actual workload downtime of a cooperative move: "
            "drain/deallocate -> claim re-placed on the reserved "
            "window (the workload restores warm from its own "
            "checkpoint from there).",
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                     60.0, 120.0, 300.0),
            registry=self.registry,
        )
        self.move_seconds = Histogram(
            "tpu_dra_migration_move_seconds",
            "End-to-end latency of one completed cooperative move: "
            "plan record written -> claim re-placed.",
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                     60.0, 120.0, 300.0),
            registry=self.registry,
        )


class AutoscaleMetrics:
    """Serving-autoscaler observability (pkg/autoscale, on the
    scheduler registry).

    A healthy controller shows ``plans_total`` rising only when demand
    genuinely drifted past the hysteresis band (a steady fleet shows
    ``converged_passes_total`` climbing with plans flat), every plan
    retiring through ``applies_total`` (``superseded_total`` counts
    operator edits winning a race -- occasional, never sustained), and
    ``active_rollouts`` returning to zero after every re-plan (the
    no-stuck-rollouts invariant the crash-resume tests pin)."""

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.plans = Counter(
            "tpu_dra_autoscale_plans_total",
            "PartitionSet re-plans started (observed demand drifted "
            "past the hysteresis band; a durable rollout record was "
            "written).",
            registry=self.registry,
        )
        self.applies = Counter(
            "tpu_dra_autoscale_applies_total",
            "Re-plans confirmed on the apiserver (the PartitionSet "
            "CRD now carries the planned content).",
            registry=self.registry,
        )
        self.superseded = Counter(
            "tpu_dra_autoscale_superseded_total",
            "Rollouts retired because a concurrent PartitionSet edit "
            "won (operator content always wins).",
            registry=self.registry,
        )
        self.converged = Counter(
            "tpu_dra_autoscale_converged_passes_total",
            "Planning passes whose desired layout already matched the "
            "active CRD (the steady state: ZERO apiserver writes).",
            registry=self.registry,
        )
        self.active_rollouts = Gauge(
            "tpu_dra_autoscale_active_rollouts",
            "Re-plan records currently in flight (0 or 1: one rollout "
            "at a time).",
            registry=self.registry,
        )
        self.rollout_seconds = Histogram(
            "tpu_dra_autoscale_rollout_seconds",
            "End-to-end latency of one confirmed re-plan: durable "
            "plan record written -> CRD content confirmed.",
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                     60.0, 120.0, 300.0),
            registry=self.registry,
        )


class ComputeDomainMetrics:
    """Cluster-level ComputeDomain status gauge (computedomain_cluster.go)."""

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.status = Gauge(
            "tpu_compute_domain_status",
            "ComputeDomain readiness (1=Ready, 0=NotReady) by domain.",
            ["namespace", "name"],
            registry=self.registry,
        )
        self.nodes = Gauge(
            "tpu_compute_domain_nodes",
            "Number of nodes registered in a ComputeDomain.",
            ["namespace", "name"],
            registry=self.registry,
        )


class MetricsServer(SimpleHTTPEndpoint):
    """Prometheus exposition server (reference prometheus_httpserver.go)
    + the pprof-analog diagnostics routes the reference mounts on the
    same mux (controller main.go:383-390): /debug/stacks (all-thread
    tracebacks), /debug/traces[/<trace-id>] (the in-process span ring,
    pkg/tracing.py), /debug/claims[/<uid-or-ns/name>] (the per-claim
    flight recorder, pkg/flightrecorder.py), /debug/telemetry (the
    per-chip telemetry ring) and /debug/fleet (the scheduler's fleet
    snapshot, both pkg/fleetstate.py) -- one listener per binary
    carries metrics AND the introspection surface, and
    ``python -m ...pkg.doctor`` crawls exactly this set into an
    incident bundle.

    Stack traces / span payloads disclose internal state, so like the
    reference's opt-in --pprof-path the debug routes are only served
    when the listener is loopback-bound or explicitly enabled
    (TPU_DRA_DEBUG_HTTP=1); production metrics bind 0.0.0.0 and keep
    them off. SIGUSR1 remains the always-available dump path."""

    def __init__(self, registry: CollectorRegistry, host: str = "127.0.0.1",
                 port: int = 0, debug_endpoints: bool | None = None):
        if debug_endpoints is None:
            import os  # noqa: PLC0415

            debug_endpoints = (
                host in ("127.0.0.1", "localhost", "::1")
                or os.environ.get("TPU_DRA_DEBUG_HTTP") == "1"
            )
        extra = None
        if debug_endpoints:
            # Late-bound: the process exporter/recorder may be swapped
            # after the server starts (tests, bench isolation).
            extra = {
                "/debug/stacks": debug_stacks_endpoint,
                "/debug/traces":
                    lambda: tracing.exporter().traces_endpoint(),
                "/debug/traces/*":
                    lambda rest: tracing.exporter().trace_endpoint(rest),
                "/debug/claims":
                    lambda: flightrecorder.default().index_endpoint(),
                "/debug/claims/*":
                    lambda rest: flightrecorder.default()
                    .claims_endpoint(rest),
                # Fleet telemetry plane (pkg/fleetstate): the node
                # plugins' per-chip sample ring and the scheduler's
                # fleet snapshot. Served on EVERY binary (an unused
                # surface returns an empty document, which is what the
                # doctor bundle expects rather than a 404).
                "/debug/telemetry":
                    lambda: fleetstate.default_ring()
                    .telemetry_endpoint(),
                "/debug/fleet":
                    lambda: fleetstate.default_fleet().fleet_endpoint(),
            }
        super().__init__(
            "/metrics",
            lambda: (200, "text/plain; version=0.0.4",
                     generate_latest(registry)),
            host=host, port=port, thread_name="metrics-http",
            extra=extra,
        )
