"""Cooperative live migration: checkpoint-then-switch claim moves.

Every migration before this module (permanent-failure recovery,
pkg/recovery; active defrag, pkg/defrag) is evict -> re-place -> cold
restart: correct, but maximally disruptive -- the workload loses
everything since its last self-managed checkpoint and the gang pays a
full cold rendezvous. This controller adds the cooperative tier of the
2502.01909 migration-cost model: when the WORKLOAD declares it can
checkpoint on demand (``resource.tpu.dra/migration-capable`` on the
claim), a move becomes a four-stage handshake with seconds of downtime
instead of minutes:

1. **Reserve** -- the destination window is chosen and reserved FIRST,
   reusing the defrag reservation-veto machinery: the scheduler fits
   every other claim around the reserved devices, so the destination
   cannot be stolen while the workload checkpoints.
2. **Signal** -- the ``resource.tpu.dra/migration-intent`` annotation
   is stamped on the claim. The workload knows to watch for it via the
   CDI env contract every prepared container carries
   (``TPU_DRA_MIGRATION_INTENT_ANNOTATION`` /
   ``TPU_DRA_MIGRATION_ACK_ANNOTATION``, kubeletplugin/cdi.py), and
   each stage lands in the claim's flight-recorder timeline.
3. **Ack** -- the workload checkpoints (the in-repo JAX stack uses its
   own ``train/checkpoint.py`` TrainCheckpointer) and writes the
   ``resource.tpu.dra/migration-ack`` annotation. No ack within
   ``TPU_DRA_MIGRATION_ACK_S`` is an ack timeout; an ack of
   ``failed`` declares a checkpoint failure.
4. **Switch** -- only now does the gang drain (the shared
   ``pkg/recovery.drain_claim`` stage), the allocation clear, and the
   scheduler re-place the claim onto the reserved window (steered by
   the same ``resource.tpu.dra/defrag-target`` hint defrag uses). The
   workload restores warm from its own checkpoint; a CD gang's
   rendezvous re-forms on the new window because every member switches
   behind the same all-acked barrier.

Progress is durable: one record per in-flight move in a
group-committed CheckpointManager under the ``migration``
TransitionPolicy (pkg/analysis/statemachine) --
absent -> MigrationDestReserved -> MigrationIntentSignaled ->
MigrationWorkloadAcked -> MigrationSwitching -> absent -- so a
controller crash at any fault seam (``migration.sync`` / ``reserve`` /
``signal`` / ``switch``) resumes idempotently from the durable stage.

**The guaranteed cold path.** EVERY failure mode degrades to the
existing PR 6 cold eviction semantics, never a stuck claim: ack
timeout, checkpoint failure, destination lost mid-handshake, racing
claim delete, controller crash. Fallback releases the reservation,
clears the contract annotations, and (when the claim still holds its
old allocation) drains and deallocates it cold -- the event-driven
scheduler re-places it anywhere, exactly as if the recovery controller
had evicted it.

Operator surface: docs/operations.md "Cooperative migration runbook"
(annotation/env contract, knob matrix, fallback semantics),
``tpu_dra_migration_*`` metrics (pkg/metrics.MigrationMetrics),
per-move flight-recorder entries.
"""

from __future__ import annotations

import logging
import threading
import time

from . import positive_float_env
from . import faults, flightrecorder
from .analysis.statemachine import (
    MIGRATION_DEST_RESERVED,
    MIGRATION_INTENT_SIGNALED,
    MIGRATION_POLICY,
    MIGRATION_SWITCHING,
    MIGRATION_WORKLOAD_ACKED,
)
from .defrag import DEFRAG_TARGET_ANNOTATION
from .kubeclient import ConflictError, KubeError, NotFoundError
from .recovery import (
    allocation_device_keys,
    allocation_nodes,
    claim_gang_id,
    claim_migration_capable,
    clear_allocation,
    drain_claim,
)

logger = logging.getLogger(__name__)

RESOURCE = ("resource.k8s.io", "v1")

#: Controller -> workload signal: stamped when the destination is
#: reserved, value ``<node>|<dev1>,<dev2>;ack-by=<unix seconds>``. The
#: workload checkpoints and acks; it keeps serving/training until the
#: drain actually lands.
MIGRATION_INTENT_ANNOTATION = "resource.tpu.dra/migration-intent"
#: Workload -> controller ack: any value acknowledges "checkpoint
#: durable, safe to switch" (conventionally the checkpoint id/step);
#: the reserved value ``failed`` declares a checkpoint failure and
#: triggers the immediate cold fallback.
MIGRATION_ACK_ANNOTATION = "resource.tpu.dra/migration-ack"
#: Ack value declaring the workload could NOT checkpoint.
ACK_FAILED = "failed"
#: Node annotation requesting cooperative evacuation: the controller
#: plans moves for every migration-capable claim allocated on an
#: annotated node (the "failing host" drain signal -- softer than the
#: recovery controller's permanent-failure taint).
EVACUATE_ANNOTATION = "resource.tpu.dra/evacuate"

# Operator knobs (docs/operations.md "Cooperative migration runbook").
#: Workload ack window: signal -> ack. Expired = ack timeout = cold
#: fallback. Size it to checkpoint time, not restore time.
MIGRATION_ACK_S = positive_float_env(
    "TPU_DRA_MIGRATION_ACK_S", default=60.0, floor=0.01)
#: Whole-move deadline (plan -> re-placed). Expired at ANY stage =
#: cold fallback with the reservation released.
MIGRATION_DEADLINE_S = positive_float_env(
    "TPU_DRA_MIGRATION_DEADLINE_S", default=300.0, floor=0.01)
MIGRATION_MAX_CONCURRENT = int(positive_float_env(
    "TPU_DRA_MIGRATION_MAX_CONCURRENT", default=2, floor=1))
#: Post-fallback quarantine: a claim whose cooperative move just fell
#: back cold is not re-planned for this long, so a persistent cause
#: (workload that never acks, checkpoint that always fails) cannot
#: spin reserve->signal->fallback forever against an evacuating node.
#: In-memory on purpose: a restarted controller may retry immediately
#: (the durable records only promise in-FLIGHT moves survive crashes).
MIGRATION_COOLDOWN_S = positive_float_env(
    "TPU_DRA_MIGRATION_COOLDOWN_S", default=30.0, floor=0.0)
#: Pause switch: "1"/"true" stops NEW moves; in-flight handshakes
#: still advance to completion or fallback (never park a half-moved
#: claim).
PAUSE_ENV = "TPU_DRA_MIGRATION_PAUSE"


def _meta(obj: dict) -> dict:
    return obj.get("metadata", {})


def node_evacuating(node: dict) -> bool:
    raw = (_meta(node).get("annotations") or {}).get(
        EVACUATE_ANNOTATION)
    return raw is not None and raw not in ("false", "False", "0")


def claim_ack(claim: dict) -> str | None:
    return (_meta(claim).get("annotations") or {}).get(
        MIGRATION_ACK_ANNOTATION)


def intent_value(node: str, devices: list[str], ack_by: float) -> str:
    return f"{node}|{','.join(devices)};ack-by={ack_by:.0f}"


class MigrationController:
    """Plans and drives cooperative checkpoint-then-switch moves;
    designed to ride the event-driven scheduler loop
    (``attach_migration``) or be driven directly (``sync_once``) by
    tests and ``bench.py --migration``."""

    #: Meta device name carrying a move record's plan payload in its
    #: ``live`` dict (target node/devices, reason, gang, clocks).
    _META_DEVICE = "migration"

    def __init__(self, kube, root: str, metrics=None,
                 ack_s: float = MIGRATION_ACK_S,
                 deadline_s: float = MIGRATION_DEADLINE_S,
                 max_concurrent: int = MIGRATION_MAX_CONCURRENT,
                 cooldown_s: float = MIGRATION_COOLDOWN_S):
        # Function-local import like pkg/recovery and pkg/defrag: pkg
        # -> kubeletplugin stays a one-way street for non-driver users.
        from ..kubeletplugin.checkpoint import (  # noqa: PLC0415
            CheckpointManager,
        )

        self.kube = kube
        self.metrics = metrics  # pkg.metrics.MigrationMetrics | None
        self.ack_s = ack_s
        self.deadline_s = deadline_s
        self.max_concurrent = max(1, int(max_concurrent))
        self.cooldown_s = max(0.0, float(cooldown_s))
        # uid -> monotonic-ish wall clock of the last cold fallback;
        # see MIGRATION_COOLDOWN_S for why this is NOT durable.
        self._last_fallback: dict[str, float] = {}
        # Durable move records under the migration TransitionPolicy:
        # the idempotent-resume anchor (see module docstring).
        self._checkpoint = CheckpointManager(
            root, transition_policy=MIGRATION_POLICY)
        self._lock = threading.Lock()
        # Device reservations derived from the durable records
        # (destination devices, keyed exactly like defrag's): the
        # scheduler's fit vetoes every OTHER claim off them, so the
        # reserved window survives the whole handshake.
        self._reservations: dict[tuple[str, str, str], str] = {}
        # Explicit move requests (uid -> reason) from operators, other
        # controllers, or the bench; in-memory on purpose -- an
        # unplanned request lost to a crash was never promised, while
        # every PLANNED move is durable.
        self._requests: dict[str, str] = {}
        # Optional informer-backed read surface
        # (pkg/schedcache.ClusterView), set by attach_migration.
        self.view = None
        self.flight = flightrecorder.default()
        self.last_sync: dict = {}
        with self._lock:
            self._rebuild_reservations_locked()
            self._active_count = len(self._checkpoint.get().claims)

    # -- scheduler surface ----------------------------------------------------

    def busy(self) -> bool:
        """True while any move record is in flight; the scheduler
        gates per-claim-event migration enqueues on this."""
        with self._lock:
            return self._active_count > 0

    def active_moves(self) -> dict[str, str]:
        """uid -> move state of every in-flight record."""
        return {uid: rec.state
                for uid, rec in self._checkpoint.get().claims.items()}

    def reservations(self) -> dict[tuple[str, str, str], str]:
        """Device key -> moving-claim uid for every reserved
        destination device. Cheap cached read for the scheduler's
        per-claim fit (merged with the defrag controller's veto)."""
        with self._lock:
            return self._reservations

    @staticmethod
    def paused() -> bool:
        import os  # noqa: PLC0415 - env read on a cold path

        return os.environ.get(PAUSE_ENV, "") in ("1", "true", "True")

    # -- move requests --------------------------------------------------------

    def request_move(self, uid: str, reason: str = "request") -> None:
        """Queue a cooperative move for one claim (target chosen at
        plan time). Other controllers and operator tooling call this;
        gang expansion happens at plan time so the WHOLE rendezvous
        moves."""
        with self._lock:
            self._requests.setdefault(uid, reason)

    # -- reads ----------------------------------------------------------------

    def _list_claims(self) -> list[dict]:
        if self.view is not None:
            return self.view.claims()
        return self.kube.list(*RESOURCE, "resourceclaims")

    def _list_slices(self) -> list[dict]:
        if self.view is not None:
            return self.view.slices()
        return self.kube.list(*RESOURCE, "resourceslices")

    def _list_nodes(self) -> list[dict]:
        try:
            if self.view is not None:
                return self.view.nodes()
            return self.kube.list("", "v1", "nodes")
        except KubeError:
            return []

    def _pods(self) -> list[dict]:
        try:
            if self.view is not None:
                return self.view.pods()
            return self.kube.list("", "v1", "pods")
        except KubeError:
            return []

    # -- sync -----------------------------------------------------------------

    def sync_once(self) -> dict:
        """One advance -> plan pass. Every stage is idempotent; a
        crash anywhere resumes from the durable records."""
        faults.fault_point("migration.sync")
        counts = {"advanced": 0, "completed": 0, "fallbacks": 0,
                  "planned": 0, "canceled": 0}
        try:
            claims = self._list_claims()
            slices = self._list_slices()
        except KubeError:
            logger.warning("migration sync: inventory list failed; "
                           "retrying next pass")
            return counts
        self._advance(claims, slices, counts)
        if not self.paused():
            self._plan(claims, slices, counts)
        active = len(self._checkpoint.get().claims)
        with self._lock:
            self._active_count = active
        if self.metrics is not None:
            self.metrics.active_moves.set(active)
        self.last_sync = counts
        return counts

    # -- planning -------------------------------------------------------------

    def _evacuation_victims(self, claims: list[dict]) -> dict[str, str]:
        """uid -> reason for migration-capable claims allocated on
        nodes annotated for evacuation."""
        nodes = self._list_nodes()
        evacuating = {_meta(n).get("name", "") for n in nodes
                      if node_evacuating(n)}
        if not evacuating:
            return {}
        out: dict[str, str] = {}
        for claim in claims:
            if not claim.get("status", {}).get("allocation"):
                continue
            if _meta(claim).get("deletionTimestamp"):
                continue
            uid = _meta(claim).get("uid", "")
            if uid and allocation_nodes(claim) & evacuating:
                out[uid] = "evacuate"
        return out

    def _plan(self, claims: list[dict], slices: list[dict],
              counts: dict) -> None:
        """Admit queued requests + evacuation victims as durable
        reserve-first records, expanded to whole gangs, under the
        concurrency cap. A claim with no reservable destination is NOT
        admitted (nothing was disrupted yet, so deferral is free); an
        explicit request for it is dropped with a log."""
        with self._lock:
            wanted = dict(self._requests)
        wanted.update(self._evacuation_victims(claims))
        if not wanted:
            return
        records = self._checkpoint.get().claims
        by_uid = {_meta(c).get("uid", ""): c for c in claims}
        # Gang expansion: a CD rendezvous moves as a unit or not at
        # all -- one member switching alone would strand the ring.
        gangs: dict[str, list[str]] = {}
        for uid, claim in by_uid.items():
            gang = claim_gang_id(claim)
            if gang and claim.get("status", {}).get("allocation"):
                gangs.setdefault(gang, []).append(uid)
        groups: dict[str, tuple[str, list[str]]] = {}
        for uid, reason in wanted.items():
            if uid in records:
                continue
            claim = by_uid.get(uid)
            if claim is None or not claim.get("status", {}).get(
                    "allocation"):
                with self._lock:
                    self._requests.pop(uid, None)
                continue
            gang = claim_gang_id(claim)
            key = gang or f"solo-{uid}"
            members = gangs.get(gang, [uid]) if gang else [uid]
            groups.setdefault(key, (reason, members))
        if not groups:
            return
        active = len(records)
        now = time.time()
        for key, (reason, members) in sorted(groups.items()):
            if any(m in records for m in members):
                continue  # a member is already mid-move
            if any(now - self._last_fallback.get(m, -1e18)
                   < self.cooldown_s for m in members):
                continue  # quarantined after a recent cold fallback
            if active + len(members) > self.max_concurrent and \
                    active > 0:
                continue  # admitted next pass, once slots free up
            if not all(claim_migration_capable(by_uid[m])
                       for m in members if m in by_uid):
                # A gang with ONE cold-only member cannot handshake as
                # a unit: the cooperative tier refuses it (the cold
                # controllers still can).
                self._drop_requests(members, reason,
                                    why="not migration-capable")
                continue
            targets = self._select_targets(
                [by_uid[m] for m in members if m in by_uid],
                slices, claims)
            if targets is None:
                self._drop_requests(members, reason,
                                    why="no reservable destination")
                continue
            faults.fault_point("migration.reserve")
            gang = None if key.startswith("solo-") else key
            for uid in members:
                claim = by_uid.get(uid)
                if claim is None:
                    continue
                node, devices, driver, pool = targets[uid]
                self._write_record(claim, MIGRATION_DEST_RESERVED, live={
                    "plannedAt": now,
                    "reason": reason,
                    "gang": gang or "",
                    "node": node,
                    "target": sorted(devices),
                    "driver": driver,
                    "pool": pool,
                    "sourceNodes": sorted(allocation_nodes(claim)),
                })
                active += 1
                counts["planned"] += 1
                logger.warning(
                    "migration planned for claim %s/%s (uid %s, "
                    "reason %s): destination %s reserved [%s]",
                    _meta(claim).get("namespace", "default"),
                    _meta(claim).get("name"), uid, reason, node,
                    ",".join(sorted(devices)))
            with self._lock:
                for uid in members:
                    self._requests.pop(uid, None)
                self._active_count = max(self._active_count, 1)
                self._rebuild_reservations_locked()
            if self.metrics is not None:
                self.metrics.plans.inc()

    def _drop_requests(self, members: list[str], reason: str,
                       why: str) -> None:
        with self._lock:
            dropped = [m for m in members
                       if self._requests.pop(m, None) is not None]
        if dropped or reason != "evacuate":
            logger.warning(
                "migration: cannot plan cooperative move for %s "
                "(reason %s): %s; claim(s) left to the cold "
                "controllers", members, reason, why)

    def _select_targets(self, group: list[dict], slices: list[dict],
                        claims: list[dict]
                        ) -> dict[str, tuple] | None:
        """Choose a destination (node, devices, driver, pool) for
        every claim in the group, disjoint across the group and free
        of every live allocation and existing reservation. None when
        any member cannot be placed -- the gang reserves as a unit."""
        taken: set[tuple[str, str, str]] = set()
        for c in claims:
            taken |= allocation_device_keys(c)
        with self._lock:
            taken |= set(self._reservations)
        avoid = {n for c in group for n in allocation_nodes(c)}
        free_by_node: dict[tuple[str, str, str], list[str]] = {}
        for s in slices:
            spec = s.get("spec", {})
            node = spec.get("nodeName") or ""
            driver = spec.get("driver", "")
            pool = spec.get("pool", {}).get("name", "")
            if not node or node in avoid:
                continue
            for dev in spec.get("devices", []) or []:
                name = dev.get("name", "")
                if (driver, pool, name) in taken:
                    continue
                free_by_node.setdefault((node, driver, pool),
                                        []).append(name)
        out: dict[str, tuple] = {}
        for claim in group:
            uid = _meta(claim).get("uid", "")
            want = max(len(allocation_device_keys(claim)), 1)
            placed = False
            for (node, driver, pool), names in sorted(
                    free_by_node.items()):
                if len(names) < want:
                    continue
                chosen = sorted(names)[:want]
                free_by_node[(node, driver, pool)] = [
                    n for n in names if n not in chosen]
                out[uid] = (node, chosen, driver, pool)
                placed = True
                break
            if not placed:
                return None
        return out

    # -- durable records ------------------------------------------------------

    def _write_record(self, claim: dict, state: str,
                      live: dict | None = None, prev=None) -> None:
        from ..kubeletplugin.checkpoint import (  # noqa: PLC0415
            CheckpointedClaim,
            CheckpointedDevice,
        )

        uid = _meta(claim).get("uid", "")
        if prev is not None:
            live = dict(prev.devices[0].live or {}) \
                if prev.devices else {}
        self._checkpoint.update_claim(uid, CheckpointedClaim(
            uid=uid,
            namespace=_meta(claim).get("namespace", "default"),
            name=_meta(claim).get("name", ""),
            state=state,
            devices=[CheckpointedDevice(
                canonical_name=self._META_DEVICE,
                kind=self._META_DEVICE, live=live or {})],
        ))
        self.flight.record(
            uid, "migration",
            alias=(f"{_meta(claim).get('namespace', 'default')}/"
                   f"{_meta(claim).get('name', '')}"),
            state=state, node=(live or {}).get("node", ""))

    @staticmethod
    def _record_meta(rec) -> dict:
        return (rec.devices[0].live or {}) if rec.devices else {}

    def _retire_record(self, uid: str) -> None:
        self._checkpoint.update_claim(uid, None)
        with self._lock:
            self._rebuild_reservations_locked()

    def _rebuild_reservations_locked(self) -> None:
        """Reservations are a pure function of the durable records, so
        a restarted controller re-derives exactly the veto set its
        predecessor held -- the destination window survives the
        crash."""
        out: dict[tuple[str, str, str], str] = {}
        for uid, rec in self._checkpoint.get().claims.items():
            meta = self._record_meta(rec)
            driver = meta.get("driver", "")
            pool = meta.get("pool", "")
            for name in meta.get("target") or []:
                out[(driver, pool, name)] = uid
        self._reservations = out

    # -- staged advance -------------------------------------------------------

    def _advance(self, claims: list[dict], slices: list[dict],
                 counts: dict) -> None:
        records = self._checkpoint.get().claims
        if not records:
            return
        by_uid = {_meta(c).get("uid", ""): c for c in claims}
        live_devices: set[tuple[str, str, str]] = set()
        for s in slices:
            spec = s.get("spec", {})
            driver = spec.get("driver", "")
            pool = spec.get("pool", {}).get("name", "")
            for dev in spec.get("devices", []) or []:
                live_devices.add((driver, pool, dev.get("name", "")))
        # Gang ack barrier: a member switches only when EVERY member
        # has acked -- one worker draining before its peers finished
        # checkpointing would corrupt the rendezvous it is part of.
        acked_by_gang: dict[str, int] = {}
        size_by_gang: dict[str, int] = {}
        for uid, rec in records.items():
            gang = self._record_meta(rec).get("gang", "")
            if not gang:
                continue
            size_by_gang[gang] = size_by_gang.get(gang, 0) + 1
            if rec.state in (MIGRATION_WORKLOAD_ACKED,
                             MIGRATION_SWITCHING):
                acked_by_gang[gang] = acked_by_gang.get(gang, 0) + 1
        now = time.time()
        pods = None
        for uid, rec in sorted(records.items()):
            claim = by_uid.get(uid)
            if claim is None or _meta(claim).get("deletionTimestamp"):
                # Racing claim delete: the move is moot; reservation
                # released, nothing to clean on the claim itself.
                self._retire_record(uid)
                counts["canceled"] += 1
                self.flight.record(uid, "migration", state="Canceled",
                                   reason="gone")
                continue
            meta = self._record_meta(rec)
            if now - float(meta.get("plannedAt", 0.0) or now) > \
                    self.deadline_s:
                self._fallback(uid, rec, claim, counts,
                               reason="deadline")
                continue
            if rec.state != MIGRATION_SWITCHING and not all(
                    (meta.get("driver", ""), meta.get("pool", ""), d)
                    in live_devices for d in meta.get("target") or []):
                # Destination lost mid-handshake (node died, slices
                # retired): the reserved window no longer exists.
                self._fallback(uid, rec, claim, counts,
                               reason="destination-lost")
                continue
            if rec.state == MIGRATION_DEST_RESERVED:
                self._signal(uid, rec, claim, counts)
            elif rec.state == MIGRATION_INTENT_SIGNALED:
                ack = claim_ack(claim)
                if ack == ACK_FAILED:
                    self._fallback(uid, rec, claim, counts,
                                   reason="checkpoint-failed")
                elif ack:
                    meta = dict(meta)
                    meta["ackedAt"] = now
                    self._write_record(claim, MIGRATION_WORKLOAD_ACKED,
                                       live=meta)
                    counts["advanced"] += 1
                    gang = meta.get("gang", "")
                    if gang:
                        acked_by_gang[gang] = \
                            acked_by_gang.get(gang, 0) + 1
                    if self.metrics is not None:
                        signaled = float(meta.get("signaledAt",
                                                  0.0) or 0.0)
                        if signaled:
                            self.metrics.ack_seconds.observe(
                                max(now - signaled, 0.0))
                elif now > float(meta.get("ackBy", 0.0) or now):
                    self._fallback(uid, rec, claim, counts,
                                   reason="ack-timeout")
            elif rec.state == MIGRATION_WORKLOAD_ACKED:
                gang = meta.get("gang", "")
                if gang and acked_by_gang.get(gang, 0) < \
                        size_by_gang.get(gang, 0):
                    continue  # barrier: peers still checkpointing
                if pods is None:
                    pods = self._pods()
                self._switch(uid, rec, claim, pods)
                counts["advanced"] += 1
            elif rec.state == MIGRATION_SWITCHING:
                self._try_retire(uid, rec, claim, counts)

    def _signal(self, uid: str, rec, claim: dict,
                counts: dict) -> None:
        """Stamp the migration-intent annotation; the ack clock starts
        at the durable IntentSignaled write, not the patch -- a crash
        between the two re-signals idempotently."""
        faults.fault_point("migration.signal")
        meta = dict(self._record_meta(rec))
        ack_by = time.time() + self.ack_s
        value = intent_value(meta.get("node", ""),
                             meta.get("target") or [], ack_by)
        try:
            self.kube.patch(
                *RESOURCE, "resourceclaims", _meta(claim)["name"],
                {"metadata": {"annotations": {
                    MIGRATION_INTENT_ANNOTATION: value}}},
                namespace=_meta(claim).get("namespace", "default"))
        except (NotFoundError, ConflictError):
            return  # re-signaled next pass
        meta["ackBy"] = ack_by
        meta["signaledAt"] = time.time()
        self._write_record(claim, MIGRATION_INTENT_SIGNALED, live=meta)
        counts["advanced"] += 1

    def _switch(self, uid: str, rec, claim: dict,
                pods: list[dict]) -> None:
        """The point of no return for THIS claim: stamp the placement
        hint, drain, deallocate. The workload's checkpoint is already
        durable (it acked), so the only downtime is drain ->
        re-placement -> warm restore."""
        faults.fault_point("migration.switch")
        meta = dict(self._record_meta(rec))
        hint = f"{meta.get('node', '')}|" + ",".join(
            meta.get("target") or [])
        try:
            self.kube.patch(
                *RESOURCE, "resourceclaims", _meta(claim)["name"],
                {"metadata": {"annotations": {
                    DEFRAG_TARGET_ANNOTATION: hint}}},
                namespace=_meta(claim).get("namespace", "default"))
        except (NotFoundError, ConflictError):
            return  # re-examined next pass
        drain_claim(self.kube, claim, pods)
        if not clear_allocation(self.kube, claim):
            return  # re-examined next pass (record still Acked)
        meta["switchedAt"] = time.time()
        self._write_record(claim, MIGRATION_SWITCHING, live=meta)
        logger.warning(
            "migration: claim %s/%s (uid %s) switched; awaiting "
            "re-placement onto %s",
            _meta(claim).get("namespace", "default"),
            _meta(claim).get("name"), uid, meta.get("node"))

    def _try_retire(self, uid: str, rec, claim: dict,
                    counts: dict) -> None:
        if not claim.get("status", {}).get("allocation"):
            return  # not yet re-placed; deadline check bounds the wait
        meta = self._record_meta(rec)
        self._clear_contract(claim)
        self._retire_record(uid)
        counts["completed"] += 1
        now = time.time()
        if self.metrics is not None:
            self.metrics.coop_moves.inc()
            switched = float(meta.get("switchedAt", 0.0) or 0.0)
            planned = float(meta.get("plannedAt", 0.0) or 0.0)
            if switched:
                self.metrics.switch_seconds.observe(
                    max(now - switched, 0.0))
            if planned:
                self.metrics.move_seconds.observe(
                    max(now - planned, 0.0))
        self.flight.record(uid, "migration", state="Migrated",
                           nodes=sorted(allocation_nodes(claim)))
        logger.warning(
            "migration: claim %s cooperatively re-placed on %s "
            "(downtime: switch -> restore)", uid,
            sorted(allocation_nodes(claim)))

    # -- the guaranteed cold path ---------------------------------------------

    def _fallback(self, uid: str, rec, claim: dict, counts: dict,
                  reason: str) -> None:
        """Degrade to the PR 6 cold eviction semantics: release the
        reservation, clear the contract annotations, and -- when the
        claim still holds its OLD allocation -- drain and deallocate
        it so the scheduler re-places it anywhere. The claim is never
        stuck: it ends allocated (pre-switch fallback keeps it
        running until the cold drain) or pending-and-schedulable."""
        state = rec.state
        if state in (MIGRATION_WORKLOAD_ACKED, MIGRATION_SWITCHING) \
                or reason in ("deadline",):
            # The workload may already have stopped for the switch:
            # finish the move COLD so it restarts somewhere rather
            # than waiting on a destination that will never form.
            if claim.get("status", {}).get("allocation"):
                drain_claim(self.kube, claim, self._pods())
                clear_allocation(self.kube, claim)
        self._clear_contract(claim)
        self._retire_record(uid)
        self._last_fallback[uid] = time.time()
        counts["fallbacks"] += 1
        if self.metrics is not None:
            self.metrics.fallbacks.labels(reason).inc()
        self.flight.record(uid, "migration", state="FellBack",
                           reason=reason, stage=state or "")
        logger.warning(
            "migration: cooperative move of claim %s fell back to the "
            "cold eviction path (%s, stage %s); reservation released",
            uid, reason, state)

    def _clear_contract(self, claim: dict) -> None:
        """Idempotent merge-null of every annotation the handshake
        stamped (intent, ack, placement hint): a stale contract must
        not re-trigger a workload checkpoint or steer a future
        re-placement."""
        try:
            self.kube.patch(
                *RESOURCE, "resourceclaims", _meta(claim)["name"],
                {"metadata": {"annotations": {
                    MIGRATION_INTENT_ANNOTATION: None,
                    MIGRATION_ACK_ANNOTATION: None,
                    DEFRAG_TARGET_ANNOTATION: None}}},
                namespace=_meta(claim).get("namespace", "default"))
        except (NotFoundError, ConflictError):
            pass
