"""DRA gRPC plumbing: plugin service + kubelet registration.

Reference: the kubeletplugin helper the reference drives
(driver.go:141, kubeletplugin.Start) -- two unix-socket gRPC services:
the DRAPlugin service (NodePrepareResources/NodeUnprepareResources) and
the pluginregistration Registration service the kubelet's plugin watcher
dials.
"""
