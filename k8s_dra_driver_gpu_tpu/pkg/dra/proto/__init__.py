"""protoc-generated messages for the DRA + registration services."""
