"""gRPC servers for the DRA plugin + registration services.

Runs over unix sockets (kubelet's plugin watcher convention):
  <plugin-dir>/<driver>.sock            DRAPlugin service
  <registry-dir>/<driver>-reg.sock      Registration service

Service handlers are wired with grpc generic handlers over the
protoc-generated messages (no grpcio-tools in this runtime).
"""

from __future__ import annotations

import logging
import os
from concurrent import futures
from typing import Callable

import grpc

from .proto import dra_plugin_pb2 as drapb
from .proto import plugin_registration_pb2 as regpb

logger = logging.getLogger(__name__)

DRA_SERVICE = "v1beta1.DRAPlugin"
REGISTRATION_SERVICE = "pluginregistration.Registration"
SUPPORTED_VERSIONS = ["v1beta1"]


class DRAPluginServicer:
    """Adapts prepare/unprepare callbacks to the wire API.

    prepare_fn(claims: list[Claim]) -> dict uid -> (devices, error) where
    devices is a list of dicts {request_names, pool_name, device_name,
    cdi_device_ids}.
    """

    def __init__(
        self,
        prepare_fn: Callable[[list], dict],
        unprepare_fn: Callable[[list], dict],
    ):
        self._prepare = prepare_fn
        self._unprepare = unprepare_fn

    def NodePrepareResources(self, request, context):  # noqa: N802
        results = self._prepare(list(request.claims))
        resp = drapb.NodePrepareResourcesResponse()
        for uid, (devices, error) in results.items():
            r = drapb.NodePrepareResourceResponse()
            if error:
                r.error = error
            for d in devices:
                dev = r.devices.add()
                dev.request_names.extend(d.get("request_names", []))
                dev.pool_name = d.get("pool_name", "")
                dev.device_name = d.get("device_name", "")
                dev.cdi_device_ids.extend(d.get("cdi_device_ids", []))
            resp.claims[uid].CopyFrom(r)
        return resp

    def NodeUnprepareResources(self, request, context):  # noqa: N802
        results = self._unprepare(list(request.claims))
        resp = drapb.NodeUnprepareResourcesResponse()
        for uid, error in results.items():
            r = drapb.NodeUnprepareResourceResponse()
            if error:
                r.error = error
            resp.claims[uid].CopyFrom(r)
        return resp

    def handler(self) -> grpc.GenericRpcHandler:
        return grpc.method_handlers_generic_handler(
            DRA_SERVICE,
            {
                "NodePrepareResources": grpc.unary_unary_rpc_method_handler(
                    self.NodePrepareResources,
                    request_deserializer=(
                        drapb.NodePrepareResourcesRequest.FromString
                    ),
                    response_serializer=(
                        drapb.NodePrepareResourcesResponse.SerializeToString
                    ),
                ),
                "NodeUnprepareResources": grpc.unary_unary_rpc_method_handler(
                    self.NodeUnprepareResources,
                    request_deserializer=(
                        drapb.NodeUnprepareResourcesRequest.FromString
                    ),
                    response_serializer=(
                        drapb.NodeUnprepareResourcesResponse.SerializeToString
                    ),
                ),
            },
        )


class RegistrationServicer:
    """Answers the kubelet plugin watcher (pluginregistration.v1)."""

    def __init__(self, driver_name: str, endpoint: str):
        self._driver = driver_name
        self._endpoint = endpoint
        self.registered = False
        self.registration_error = ""

    def GetInfo(self, request, context):  # noqa: N802
        info = regpb.PluginInfo()
        info.type = "DRAPlugin"
        info.name = self._driver
        info.endpoint = self._endpoint
        info.supported_versions.extend(SUPPORTED_VERSIONS)
        return info

    def NotifyRegistrationStatus(self, request, context):  # noqa: N802
        self.registered = request.plugin_registered
        self.registration_error = request.error
        if not request.plugin_registered:
            logger.error("kubelet registration failed: %s", request.error)
        return regpb.RegistrationStatusResponse()

    def handler(self) -> grpc.GenericRpcHandler:
        return grpc.method_handlers_generic_handler(
            REGISTRATION_SERVICE,
            {
                "GetInfo": grpc.unary_unary_rpc_method_handler(
                    self.GetInfo,
                    request_deserializer=regpb.InfoRequest.FromString,
                    response_serializer=regpb.PluginInfo.SerializeToString,
                ),
                "NotifyRegistrationStatus": grpc.unary_unary_rpc_method_handler(
                    self.NotifyRegistrationStatus,
                    request_deserializer=regpb.RegistrationStatus.FromString,
                    response_serializer=(
                        regpb.RegistrationStatusResponse.SerializeToString
                    ),
                ),
            },
        )


class PluginServer:
    """Hosts both services on their unix sockets."""

    def __init__(
        self,
        driver_name: str,
        plugin_dir: str,
        registry_dir: str,
        prepare_fn,
        unprepare_fn,
    ):
        os.makedirs(plugin_dir, exist_ok=True)
        os.makedirs(registry_dir, exist_ok=True)
        self.plugin_socket = os.path.join(plugin_dir, f"{driver_name}.sock")
        self.registry_socket = os.path.join(
            registry_dir, f"{driver_name}-reg.sock"
        )
        for sock in (self.plugin_socket, self.registry_socket):
            if os.path.exists(sock):
                os.unlink(sock)

        self.dra = DRAPluginServicer(prepare_fn, unprepare_fn)
        self.registration = RegistrationServicer(
            driver_name, self.plugin_socket
        )

        self._plugin_server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=4)
        )
        self._plugin_server.add_generic_rpc_handlers((self.dra.handler(),))
        self._plugin_server.add_insecure_port(f"unix://{self.plugin_socket}")

        self._registry_server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=2)
        )
        self._registry_server.add_generic_rpc_handlers(
            (self.registration.handler(),)
        )
        self._registry_server.add_insecure_port(
            f"unix://{self.registry_socket}"
        )

    def start(self) -> None:
        self._plugin_server.start()
        self._registry_server.start()

    def stop(self, grace: float = 2.0) -> None:
        self._plugin_server.stop(grace)
        self._registry_server.stop(grace)
        for sock in (self.plugin_socket, self.registry_socket):
            try:
                os.unlink(sock)
            except FileNotFoundError:
                pass


def dra_client_stubs(socket_path: str):
    """A raw client for tests / healthchecks: returns (channel, call_fns)."""
    channel = grpc.insecure_channel(f"unix://{socket_path}")
    prepare = channel.unary_unary(
        f"/{DRA_SERVICE}/NodePrepareResources",
        request_serializer=drapb.NodePrepareResourcesRequest.SerializeToString,
        response_deserializer=drapb.NodePrepareResourcesResponse.FromString,
    )
    unprepare = channel.unary_unary(
        f"/{DRA_SERVICE}/NodeUnprepareResources",
        request_serializer=(
            drapb.NodeUnprepareResourcesRequest.SerializeToString
        ),
        response_deserializer=(
            drapb.NodeUnprepareResourcesResponse.FromString
        ),
    )
    return channel, prepare, unprepare


def registration_client_stubs(socket_path: str):
    channel = grpc.insecure_channel(f"unix://{socket_path}")
    get_info = channel.unary_unary(
        f"/{REGISTRATION_SERVICE}/GetInfo",
        request_serializer=regpb.InfoRequest.SerializeToString,
        response_deserializer=regpb.PluginInfo.FromString,
    )
    notify = channel.unary_unary(
        f"/{REGISTRATION_SERVICE}/NotifyRegistrationStatus",
        request_serializer=regpb.RegistrationStatus.SerializeToString,
        response_deserializer=regpb.RegistrationStatusResponse.FromString,
    )
    return channel, get_info, notify
