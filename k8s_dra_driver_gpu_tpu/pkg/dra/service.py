"""gRPC servers for the DRA plugin + registration services.

Runs over unix sockets (kubelet's plugin watcher convention):
  <plugin-dir>/<driver>.sock            DRAPlugin service
  <registry-dir>/<driver>-reg.sock      Registration service

Service handlers are wired with grpc generic handlers over the
protoc-generated messages (no grpcio-tools in this runtime).
"""

from __future__ import annotations

import logging
import os
from concurrent import futures
from typing import Callable

import grpc

from .proto import dra_plugin_pb2 as drapb
from .proto import dra_plugin_v1_pb2 as drapbv1
from .proto import plugin_registration_pb2 as regpb

logger = logging.getLogger(__name__)

DRA_SERVICE_V1 = "v1.DRAPlugin"
DRA_SERVICE_V1BETA1 = "v1beta1.DRAPlugin"
DRA_SERVICE = DRA_SERVICE_V1BETA1  # compat alias for older callers
REGISTRATION_SERVICE = "pluginregistration.Registration"
# Registration advertises SERVICE NAMES, not bare versions -- the
# kubelet DRA plugin manager matches on e.g. "v1beta1.DRAPlugin"
# (ref noderegistrar.go:39). v1 first: newest the kubelet supports wins.
SUPPORTED_SERVICES = [DRA_SERVICE_V1, DRA_SERVICE_V1BETA1]

_SERVICE_PB = {
    DRA_SERVICE_V1: drapbv1,
    DRA_SERVICE_V1BETA1: drapb,
}


class DRAPluginServicer:
    """Adapts prepare/unprepare callbacks to the wire API for ONE
    service version; the plugin socket hosts one instance per version
    (the reference registers v1 and a v1beta1 wrapper side by side,
    draplugin.go:792-801).

    prepare_fn(claims: list[Claim]) -> dict uid -> (devices, error) where
    devices is a list of dicts {request_names, pool_name, device_name,
    cdi_device_ids, share_id?}; share_id only rides the v1 wire (the
    field does not exist pre-v1).
    """

    def __init__(
        self,
        prepare_fn: Callable[[list], dict],
        unprepare_fn: Callable[[list], dict],
        service: str = DRA_SERVICE_V1BETA1,
    ):
        self._prepare = prepare_fn
        self._unprepare = unprepare_fn
        self._service = service
        self._pb = _SERVICE_PB[service]

    def NodePrepareResources(self, request, context):  # noqa: N802
        results = self._prepare(list(request.claims))
        resp = self._pb.NodePrepareResourcesResponse()
        for uid, (devices, error) in results.items():
            r = self._pb.NodePrepareResourceResponse()
            if error:
                r.error = error
            for d in devices:
                dev = r.devices.add()
                dev.request_names.extend(d.get("request_names", []))
                dev.pool_name = d.get("pool_name", "")
                dev.device_name = d.get("device_name", "")
                dev.cdi_device_ids.extend(d.get("cdi_device_ids", []))
                if d.get("share_id") and self._service == DRA_SERVICE_V1:
                    dev.share_id = d["share_id"]
            resp.claims[uid].CopyFrom(r)
        return resp

    def NodeUnprepareResources(self, request, context):  # noqa: N802
        results = self._unprepare(list(request.claims))
        resp = self._pb.NodeUnprepareResourcesResponse()
        for uid, error in results.items():
            r = self._pb.NodeUnprepareResourceResponse()
            if error:
                r.error = error
            resp.claims[uid].CopyFrom(r)
        return resp

    def handler(self) -> grpc.GenericRpcHandler:
        pb = self._pb
        return grpc.method_handlers_generic_handler(
            self._service,
            {
                "NodePrepareResources": grpc.unary_unary_rpc_method_handler(
                    self.NodePrepareResources,
                    request_deserializer=(
                        pb.NodePrepareResourcesRequest.FromString
                    ),
                    response_serializer=(
                        pb.NodePrepareResourcesResponse.SerializeToString
                    ),
                ),
                "NodeUnprepareResources": grpc.unary_unary_rpc_method_handler(
                    self.NodeUnprepareResources,
                    request_deserializer=(
                        pb.NodeUnprepareResourcesRequest.FromString
                    ),
                    response_serializer=(
                        pb.NodeUnprepareResourcesResponse.SerializeToString
                    ),
                ),
            },
        )


class RegistrationServicer:
    """Answers the kubelet plugin watcher (pluginregistration.v1)."""

    def __init__(self, driver_name: str, endpoint: str):
        self._driver = driver_name
        self._endpoint = endpoint
        self.registered = False
        self.registration_error = ""

    def GetInfo(self, request, context):  # noqa: N802
        info = regpb.PluginInfo()
        info.type = "DRAPlugin"
        info.name = self._driver
        info.endpoint = self._endpoint
        info.supported_versions.extend(SUPPORTED_SERVICES)
        return info

    def NotifyRegistrationStatus(self, request, context):  # noqa: N802
        self.registered = request.plugin_registered
        self.registration_error = request.error
        if not request.plugin_registered:
            logger.error("kubelet registration failed: %s", request.error)
        return regpb.RegistrationStatusResponse()

    def handler(self) -> grpc.GenericRpcHandler:
        return grpc.method_handlers_generic_handler(
            REGISTRATION_SERVICE,
            {
                "GetInfo": grpc.unary_unary_rpc_method_handler(
                    self.GetInfo,
                    request_deserializer=regpb.InfoRequest.FromString,
                    response_serializer=regpb.PluginInfo.SerializeToString,
                ),
                "NotifyRegistrationStatus": grpc.unary_unary_rpc_method_handler(
                    self.NotifyRegistrationStatus,
                    request_deserializer=regpb.RegistrationStatus.FromString,
                    response_serializer=(
                        regpb.RegistrationStatusResponse.SerializeToString
                    ),
                ),
            },
        )


class PluginServer:
    """Hosts both services on their unix sockets."""

    def __init__(
        self,
        driver_name: str,
        plugin_dir: str,
        registry_dir: str,
        prepare_fn,
        unprepare_fn,
    ):
        os.makedirs(plugin_dir, exist_ok=True)
        os.makedirs(registry_dir, exist_ok=True)
        self.plugin_socket = os.path.join(plugin_dir, f"{driver_name}.sock")
        self.registry_socket = os.path.join(
            registry_dir, f"{driver_name}-reg.sock"
        )
        for sock in (self.plugin_socket, self.registry_socket):
            if os.path.exists(sock):
                os.unlink(sock)

        # Both API versions on ONE socket (ref draplugin.go:792-801);
        # self.dra keeps naming the v1beta1 instance for older callers.
        self.dra_v1 = DRAPluginServicer(
            prepare_fn, unprepare_fn, service=DRA_SERVICE_V1
        )
        self.dra = DRAPluginServicer(
            prepare_fn, unprepare_fn, service=DRA_SERVICE_V1BETA1
        )
        self.registration = RegistrationServicer(
            driver_name, self.plugin_socket
        )

        self._plugin_server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=4)
        )
        self._plugin_server.add_generic_rpc_handlers(
            (self.dra_v1.handler(), self.dra.handler())
        )
        self._plugin_server.add_insecure_port(f"unix://{self.plugin_socket}")

        self._registry_server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=2)
        )
        self._registry_server.add_generic_rpc_handlers(
            (self.registration.handler(),)
        )
        self._registry_server.add_insecure_port(
            f"unix://{self.registry_socket}"
        )

    def start(self) -> None:
        self._plugin_server.start()
        self._registry_server.start()

    def stop(self, grace: float = 2.0) -> None:
        self._plugin_server.stop(grace)
        self._registry_server.stop(grace)
        for sock in (self.plugin_socket, self.registry_socket):
            try:
                os.unlink(sock)
            except FileNotFoundError:
                pass


def dra_client_stubs(socket_path: str, service: str = DRA_SERVICE_V1BETA1):
    """A raw client for tests / healthchecks: returns (channel, call_fns).
    ``service`` picks the negotiated API version, as a kubelet would
    from the advertised SUPPORTED_SERVICES."""
    pb = _SERVICE_PB[service]
    channel = grpc.insecure_channel(f"unix://{socket_path}")
    prepare = channel.unary_unary(
        f"/{service}/NodePrepareResources",
        request_serializer=pb.NodePrepareResourcesRequest.SerializeToString,
        response_deserializer=pb.NodePrepareResourcesResponse.FromString,
    )
    unprepare = channel.unary_unary(
        f"/{service}/NodeUnprepareResources",
        request_serializer=(
            pb.NodeUnprepareResourcesRequest.SerializeToString
        ),
        response_deserializer=(
            pb.NodeUnprepareResourcesResponse.FromString
        ),
    )
    return channel, prepare, unprepare


def registration_client_stubs(socket_path: str):
    channel = grpc.insecure_channel(f"unix://{socket_path}")
    get_info = channel.unary_unary(
        f"/{REGISTRATION_SERVICE}/GetInfo",
        request_serializer=regpb.InfoRequest.SerializeToString,
        response_deserializer=regpb.PluginInfo.FromString,
    )
    notify = channel.unary_unary(
        f"/{REGISTRATION_SERVICE}/NotifyRegistrationStatus",
        request_serializer=regpb.RegistrationStatus.SerializeToString,
        response_deserializer=regpb.RegistrationStatusResponse.FromString,
    )
    return channel, get_info, notify
