"""Rate-limited retrying work queue with per-item callbacks.

Reference: pkg/workqueue/workqueue.go (wrapper over the k8s typed
rate-limited workqueue; limiter presets at :40-58 -- prepare/unprepare
250ms->3s exponential plus a global 5 rps / burst 10 bucket; compute-domain
daemon 5ms->6s exponential with 50% jitter, jitterlimiter.go; controller
default) and the compute-domain plugin's retry engine
(cmd/compute-domain-kubelet-plugin/driver.go:40-233: bounded retries via
ErrorRetryMaxTimeout, permanentError short-circuit).

Design notes (TPU build): a small threaded queue. Items are hashable keys
with an attached callback; failures re-enqueue with exponential backoff
until the limiter's max delay; ``PermanentError`` short-circuits retries.
"""

from __future__ import annotations

import heapq
import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

logger = logging.getLogger(__name__)


class PermanentError(Exception):
    """Wraps an error that must not be retried.

    Reference: the CD plugin's permanentError (driver.go:56-60).
    """

    def __init__(self, cause: BaseException | str):
        super().__init__(str(cause))
        self.cause = cause if isinstance(cause, BaseException) else None


@dataclass(frozen=True)
class RateLimiter:
    """Per-item exponential backoff with optional jitter and global rps cap."""

    base_delay: float = 0.25
    max_delay: float = 3.0
    jitter: float = 0.0  # fraction of delay added uniformly at random
    global_rps: float | None = None
    global_burst: int = 1
    # Total elapsed-time budget for retrying one item; None = unbounded.
    # Reference: ErrorRetryMaxTimeout=45s (CD plugin driver.go:40-52).
    retry_timeout: float | None = None

    def delay_for(self, failures: int) -> float:
        # Cap the exponent so a persistently failing item can't grow an
        # unbounded integer before the clamp.
        exp = min(max(failures - 1, 0), 62)
        d = min(self.base_delay * (2 ** exp), self.max_delay)
        if self.jitter:
            d += d * self.jitter * random.random()
        return d


# Presets mirroring the reference's limiter catalog (workqueue.go:40-58).
PREP_UNPREP_LIMITER = RateLimiter(
    base_delay=0.25, max_delay=3.0, global_rps=5.0, global_burst=10,
    retry_timeout=45.0,
)
DOMAIN_DAEMON_LIMITER = RateLimiter(base_delay=0.005, max_delay=6.0, jitter=0.5)
CONTROLLER_DEFAULT_LIMITER = RateLimiter(base_delay=0.005, max_delay=1.0)


@dataclass(order=True)
class _Scheduled:
    when: float
    seq: int
    key: Any = field(compare=False)


class WorkQueue:
    """A retrying queue. ``enqueue(key, fn)`` runs ``fn(key)`` on a worker;
    exceptions re-enqueue with backoff; PermanentError drops the item.

    ``serialize=False`` allows multiple workers (reference CD plugin uses
    Serialize(false) because channel-Prepares are codependent with the
    daemon's Prepare, driver.go:89-96).
    """

    def __init__(
        self,
        limiter: RateLimiter = CONTROLLER_DEFAULT_LIMITER,
        workers: int = 1,
        name: str = "workqueue",
        on_drop: Callable[[Any, BaseException], None] | None = None,
    ):
        self._limiter = limiter
        self._name = name
        self._on_drop = on_drop
        self._heap: list[_Scheduled] = []
        self._failures: dict[Any, int] = {}
        self._first_failure: dict[Any, float] = {}
        self._pending: set[Any] = set()  # keys queued or running (dedupe)
        self._running: set[Any] = set()  # keys currently in a callback
        # Latest callback per pending key: an enqueue for a queued key
        # (including one waiting out a retry backoff) swaps in the fresh
        # callback; the heap holds keys only.
        self._fn: dict[Any, Callable[[Any], None]] = {}
        # Keys re-enqueued while running: processed again after the
        # in-flight callback returns (k8s workqueue "dirty" semantics),
        # so a watch event racing a reconcile is never silently dropped.
        self._dirty: set[Any] = set()
        self._cv = threading.Condition()
        self._seq = 0
        self._shutdown = False
        self._tokens = float(limiter.global_burst)
        self._last_refill = time.monotonic()
        self._threads = [
            threading.Thread(target=self._run, name=f"{name}-{i}", daemon=True)
            for i in range(max(workers, 1))
        ]
        for t in self._threads:
            t.start()

    # -- public API -----------------------------------------------------------

    def enqueue(self, key: Any, fn: Callable[[Any], None]) -> None:
        """Schedule fn(key) to run now. Deduplicates by key while queued
        (the fresh fn replaces the queued one); an enqueue for a key
        whose callback is mid-flight marks it dirty and re-runs it (with
        the new fn) after the callback returns."""
        with self._cv:
            if self._shutdown:
                return
            self._fn[key] = fn
            if key in self._running:
                self._dirty.add(key)
                return
            if key in self._pending:
                return  # already queued; it will run with the fresh fn
            self._pending.add(key)
            self._push(key, delay=0.0)

    def forget(self, key: Any) -> None:
        """Reset the failure count for key (after a success elsewhere)."""
        with self._cv:
            self._failures.pop(key, None)
            self._first_failure.pop(key, None)

    def len(self) -> int:
        with self._cv:
            return len(self._heap)

    def shutdown(self, wait: bool = True) -> None:
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()
        if wait:
            for t in self._threads:
                t.join(timeout=5.0)

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Block until no items are queued or running (test helper)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._cv:
                if not self._pending and not self._heap:
                    return True
            time.sleep(0.005)
        return False

    # -- internals ------------------------------------------------------------

    def _push(self, key: Any, delay: float) -> None:
        self._seq += 1
        heapq.heappush(
            self._heap, _Scheduled(time.monotonic() + delay, self._seq, key)
        )
        self._cv.notify()

    def _take_token(self) -> float:
        """Global token bucket (reference: 5 rps / burst 10 on prep queues).

        Returns seconds to wait before running (0 if a token was available).
        """
        if self._limiter.global_rps is None:
            return 0.0
        now = time.monotonic()
        self._tokens = min(
            self._limiter.global_burst,
            self._tokens + (now - self._last_refill) * self._limiter.global_rps,
        )
        self._last_refill = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return 0.0
        return (1.0 - self._tokens) / self._limiter.global_rps

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._shutdown and (
                    not self._heap or self._heap[0].when > time.monotonic()
                ):
                    timeout = None
                    if self._heap:
                        timeout = max(self._heap[0].when - time.monotonic(), 0)
                    self._cv.wait(timeout=timeout)
                if self._shutdown:
                    return
                wait = self._take_token()
                if wait > 0:
                    item = heapq.heappop(self._heap)
                    item.when = time.monotonic() + wait
                    heapq.heappush(self._heap, item)
                    continue
                item = heapq.heappop(self._heap)
                self._running.add(item.key)
                fn = self._fn.get(item.key)
            try:
                if fn is not None:
                    fn(item.key)
            except PermanentError as e:
                self._drop(item.key, e)
            except BaseException as e:  # noqa: BLE001 - retry loop boundary
                now = time.monotonic()
                with self._cv:
                    first = self._first_failure.setdefault(item.key, now)
                    exhausted = (
                        self._limiter.retry_timeout is not None
                        and now - first >= self._limiter.retry_timeout
                    )
                    if not exhausted:
                        n = self._failures.get(item.key, 0) + 1
                        self._failures[item.key] = n
                        self._running.discard(item.key)
                        # A retry is scheduled; it looks the callback up
                        # at run time, so a fresh fn enqueued mid-flight
                        # (or mid-backoff) is picked up automatically.
                        self._dirty.discard(item.key)
                        self._push(item.key, self._limiter.delay_for(n))
                if exhausted:
                    logger.warning(
                        "%s: retry budget (%.1fs) exhausted for %r",
                        self._name, self._limiter.retry_timeout, item.key,
                    )
                    self._drop(item.key, e)
                else:
                    logger.warning(
                        "%s: %r failed (attempt %d), retrying: %s",
                        self._name, item.key, n, e,
                    )
            else:
                with self._cv:
                    self._failures.pop(item.key, None)
                    self._first_failure.pop(item.key, None)
                    self._running.discard(item.key)
                    self._retire_or_requeue_locked(item.key)

    def _retire_or_requeue_locked(self, key: Any) -> None:
        """Re-push a dirty key, else retire it from pending. Caller holds
        the lock."""
        if key in self._dirty and not self._shutdown:
            self._dirty.discard(key)
            self._push(key, delay=0.0)  # key stays in _pending
        else:
            self._dirty.discard(key)
            self._pending.discard(key)
            self._fn.pop(key, None)

    def _drop(self, key: Any, err: BaseException) -> None:
        with self._cv:
            self._failures.pop(key, None)
            self._first_failure.pop(key, None)
            self._running.discard(key)
            self._retire_or_requeue_locked(key)
        if self._on_drop:
            self._on_drop(key, err)
        else:
            logger.error("%s: dropping %r: %s", self._name, key, err)
