"""Rate-limited retrying work queue with per-item callbacks.

Reference: pkg/workqueue/workqueue.go (wrapper over the k8s typed
rate-limited workqueue; limiter presets at :40-58 -- prepare/unprepare
250ms->3s exponential plus a global 5 rps / burst 10 bucket; compute-domain
daemon 5ms->6s exponential with 50% jitter, jitterlimiter.go; controller
default) and the compute-domain plugin's retry engine
(cmd/compute-domain-kubelet-plugin/driver.go:40-233: bounded retries via
ErrorRetryMaxTimeout, permanentError short-circuit).

Design notes (TPU build): a small threaded queue. Items are hashable keys
with an attached callback; failures re-enqueue with exponential backoff
until the limiter's max delay; ``PermanentError`` short-circuits retries.

Scale-out additions (scheduler scale-out PR):

- **Keyed shard affinity** (``shard_of``): every key maps to a shard and
  every shard maps to exactly one worker, so keys sharing a shard are
  processed serially while disjoint shards drain in parallel. The
  scheduler hashes claim/pod namespace+name into data shards and pins
  control keys (full resync, recovery, inventory) to a dedicated shard,
  which is what keeps the eviction controller from queueing behind a
  claim flood.
- **Batch draining** (``take_ready`` / ``finish``): a running callback
  may claim additional due same-shard keys and process them in one
  amortized pass (one inventory snapshot per batch instead of one per
  claim), then report each extra key's outcome via ``finish``.
- **Hot-key fairness**: a key re-dirtied in a tight loop (an object
  whose every reconcile triggers another event for itself) is re-run
  immediately only ``hot_threshold`` consecutive times; past that its
  requeue delay escalates exponentially (capped at the limiter's max
  delay), so one hot key cannot monopolize a worker while cold keys
  wait. The streak resets the first time the key retires clean.
- **Work stealing** (``steal``): an idle worker may claim ready keys
  from the DEEPEST sibling heap (under the shared owner lock), so a
  pathological flood hashing onto one shard -- e.g. a single-namespace
  claim storm whose ns/name keys all land on one data worker -- drains
  across the pool instead of serializing. Only keys the ``steal``
  predicate admits are eligible (the scheduler excludes control keys),
  and per-KEY exclusion is preserved: a key lives in exactly one heap
  and ``_running`` blocks concurrent re-runs, so stealing changes
  placement, never serialization semantics.
- **Observability** (``metrics``): per-shard depth, queue-wait
  histogram, retry/drop/hot-backoff/steal counters via a duck-typed
  sink (pkg/metrics.WorkQueueMetrics).
"""

from __future__ import annotations

import heapq
import logging
import random
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable

logger = logging.getLogger(__name__)


class PermanentError(Exception):
    """Wraps an error that must not be retried.

    Reference: the CD plugin's permanentError (driver.go:56-60).
    """

    def __init__(self, cause: BaseException | str):
        super().__init__(str(cause))
        self.cause = cause if isinstance(cause, BaseException) else None


@dataclass(frozen=True)
class RateLimiter:
    """Per-item exponential backoff with optional jitter and global rps cap."""

    base_delay: float = 0.25
    max_delay: float = 3.0
    jitter: float = 0.0  # fraction of delay added uniformly at random
    global_rps: float | None = None
    global_burst: int = 1
    # Total elapsed-time budget for retrying one item; None = unbounded.
    # Reference: ErrorRetryMaxTimeout=45s (CD plugin driver.go:40-52).
    retry_timeout: float | None = None

    def delay_for(self, failures: int) -> float:
        # Cap the exponent so a persistently failing item can't grow an
        # unbounded integer before the clamp.
        exp = min(max(failures - 1, 0), 62)
        d = min(self.base_delay * (2 ** exp), self.max_delay)
        if self.jitter:
            d += d * self.jitter * random.random()
        return d


# Presets mirroring the reference's limiter catalog (workqueue.go:40-58).
PREP_UNPREP_LIMITER = RateLimiter(
    base_delay=0.25, max_delay=3.0, global_rps=5.0, global_burst=10,
    retry_timeout=45.0,
)
DOMAIN_DAEMON_LIMITER = RateLimiter(base_delay=0.005, max_delay=6.0, jitter=0.5)
CONTROLLER_DEFAULT_LIMITER = RateLimiter(base_delay=0.005, max_delay=1.0)


def stable_shard_hash(value: Any) -> int:
    """Deterministic (cross-process) non-negative hash for shard
    routing; python's builtin hash() is salted per process."""
    if isinstance(value, int):
        return abs(value)
    return zlib.crc32(repr(value).encode("utf-8", "replace"))


@dataclass(order=True)
class _Scheduled:
    when: float
    seq: int
    key: Any = field(compare=False)
    # Enqueue timestamp for the queue-wait histogram (includes any
    # retry/hot backoff the item waited out).
    born: float = field(compare=False, default=0.0)


class WorkQueue:
    """A retrying queue. ``enqueue(key, fn)`` runs ``fn(key)`` on a worker;
    exceptions re-enqueue with backoff; PermanentError drops the item.

    ``shard_of(key)`` (optional) routes every key to a stable shard;
    a shard is owned by exactly one worker (``stable_shard_hash(shard)
    % workers``; an int shard is taken modulo directly so callers can
    pin shards to workers). Without it, keys hash over all workers.

    ``serialize=False`` allows multiple workers (reference CD plugin uses
    Serialize(false) because channel-Prepares are codependent with the
    daemon's Prepare, driver.go:89-96).
    """

    # Consecutive dirty-requeues a key may burn at zero delay before the
    # fairness escalation kicks in.
    HOT_THRESHOLD = 3
    HOT_BASE_DELAY = 0.02

    def __init__(
        self,
        limiter: RateLimiter = CONTROLLER_DEFAULT_LIMITER,
        workers: int = 1,
        name: str = "workqueue",
        on_drop: Callable[[Any, BaseException], None] | None = None,
        shard_of: Callable[[Any], Any] | None = None,
        metrics=None,
        steal: Callable[[Any], bool] | None = None,
        may_steal: Callable[[int], bool] | None = None,
    ):
        self._limiter = limiter
        self._name = name
        self._on_drop = on_drop
        self._shard_of = shard_of
        self._metrics = metrics
        # Work-stealing predicate: keys it admits may be migrated from
        # a backlogged sibling's heap to an idle worker. None (the
        # default) disables stealing entirely -- strict shard->worker
        # placement, the historical behavior. ``may_steal(worker)``
        # additionally gates WHICH workers act as thieves (the
        # scheduler keeps its dedicated control worker out, so control
        # keys never queue behind stolen claim work).
        self._steal = steal
        self._may_steal = may_steal
        self._idle: set[int] = set()
        self.workers = max(workers, 1)
        self._heaps: list[list[_Scheduled]] = [
            [] for _ in range(self.workers)]
        self._failures: dict[Any, int] = {}
        self._first_failure: dict[Any, float] = {}
        self._pending: set[Any] = set()  # keys queued or running (dedupe)
        self._running: set[Any] = set()  # keys currently in a callback
        # Latest callback per pending key: an enqueue for a queued key
        # (including one waiting out a retry backoff) swaps in the fresh
        # callback; the heap holds keys only.
        self._fn: dict[Any, Callable[[Any], None]] = {}
        # Keys re-enqueued while running: processed again after the
        # in-flight callback returns (k8s workqueue "dirty" semantics),
        # so a watch event racing a reconcile is never silently dropped.
        self._dirty: set[Any] = set()
        # Consecutive dirty-requeue streak per key (fairness escalation).
        self._hot: dict[Any, int] = {}
        # One base lock; per-worker conditions on it so a push wakes
        # ONLY the owning worker instead of thundering the whole pool.
        base = threading.RLock()
        self._cv = threading.Condition(base)
        self._worker_cv = [threading.Condition(base)
                           for _ in range(max(workers, 1))]
        # Lock-free approximate queued-size (hot-path metrics read).
        self._size = 0
        self._seq = 0
        self._shutdown = False
        self._tokens = float(limiter.global_burst)
        self._last_refill = time.monotonic()
        self._tls = threading.local()
        self._threads = [
            threading.Thread(target=self._run, args=(i,),
                             name=f"{name}-{i}", daemon=True)
            for i in range(self.workers)
        ]
        for t in self._threads:
            t.start()

    # -- public API -----------------------------------------------------------

    def worker_of(self, key: Any) -> int:
        """The worker index that owns ``key``'s shard."""
        if self.workers == 1:
            return 0
        shard = self._shard_of(key) if self._shard_of is not None else key
        return stable_shard_hash(shard) % self.workers

    def enqueue(self, key: Any, fn: Callable[[Any], None]) -> None:
        """Schedule fn(key) to run now. Deduplicates by key while queued
        (the fresh fn replaces the queued one); an enqueue for a key
        whose callback is mid-flight marks it dirty and re-runs it (with
        the new fn) after the callback returns."""
        with self._cv:
            if self._shutdown:
                return
            self._fn[key] = fn
            if key in self._running:
                self._dirty.add(key)
                return
            if key in self._pending:
                return  # already queued; it will run with the fresh fn
            self._pending.add(key)
            self._push(key, delay=0.0)

    def forget(self, key: Any) -> None:
        """Reset the failure count for key (after a success elsewhere)."""
        with self._cv:
            self._failures.pop(key, None)
            self._first_failure.pop(key, None)

    def len(self) -> int:
        """Approximate queued size, read without the lock -- this sits
        on the enqueue hot path (dirty-queue depth gauge)."""
        return self._size

    def current_wait(self) -> float | None:
        """Enqueue-to-run wait (seconds, including any retry / hot-key
        backoff) of the item the CALLING worker is currently executing;
        None outside a queue callback. Batch-taken keys (take_ready)
        share the primary item's wait -- they drained in the same
        amortized pass. This is the per-item twin of the aggregate
        wait histogram: consumers (the scheduler's claim-SLO "queued"
        phase) attribute one item's latency instead of a distribution."""
        return getattr(self._tls, "wait", None)

    def depth(self, worker: int) -> int:
        with self._cv:
            return len(self._heaps[worker])

    def take_ready(self, pred: Callable[[Any], bool],
                   limit: int) -> list[Any]:
        """Claim up to ``limit`` additional DUE keys from the calling
        worker's own heap (its home shard, plus any keys work stealing
        migrated in) matching ``pred``, marking them running. Per-key
        exclusion rests on the ``_running`` set, not shard residency,
        so stolen keys batch exactly like home keys. Only callable from inside a
        queue callback; the caller must report each taken key's outcome
        via :meth:`finish`. Batch takes bypass the global token bucket
        (the batch exists to amortize work, not to multiply it)."""
        idx = getattr(self._tls, "worker", None)
        if idx is None or limit <= 0:
            return []
        taken: list[Any] = []
        now = time.monotonic()
        with self._cv:
            heap = self._heaps[idx]
            keep: list[_Scheduled] = []
            for item in heap:
                if (len(taken) < limit and item.when <= now
                        and item.key not in self._running
                        and pred(item.key)):
                    taken.append(item.key)
                    self._running.add(item.key)
                    if self._metrics is not None:
                        self._metrics.observe_wait(now - item.born)
                else:
                    keep.append(item)
            if taken:
                # In place: the worker loop holds an alias to this list.
                heap[:] = keep
                heapq.heapify(heap)
                self._size -= len(taken)
                self._observe_depth_locked(idx)
        return taken

    def finish(self, key: Any, error: BaseException | None = None) -> None:
        """Report the outcome of a key claimed via :meth:`take_ready`
        (success retires or re-runs a dirty key; an error re-enqueues
        with the same backoff discipline as a worker-loop failure)."""
        self._after_run(key, error)

    def shutdown(self, wait: bool = True) -> None:
        with self._cv:
            self._shutdown = True
            for cv in self._worker_cv:
                cv.notify_all()
        if wait:
            for t in self._threads:
                t.join(timeout=5.0)

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Block until no items are queued or running (test helper)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._cv:
                if not self._pending and not any(self._heaps):
                    return True
            time.sleep(0.005)
        return False

    # -- internals ------------------------------------------------------------

    def _push(self, key: Any, delay: float) -> None:
        self._seq += 1
        idx = self.worker_of(key)
        now = time.monotonic()
        heapq.heappush(
            self._heaps[idx],
            _Scheduled(now + delay, self._seq, key, born=now))
        self._size += 1
        self._observe_depth_locked(idx)
        self._worker_cv[idx].notify()
        if self._steal is not None and delay <= 0:
            # Give one idle sibling a chance to steal if the owner is
            # backlogged; a thief that finds nothing just re-sleeps.
            for j in self._idle:
                if j != idx and (self._may_steal is None
                                 or self._may_steal(j)):
                    self._worker_cv[j].notify()
                    break

    def _observe_depth_locked(self, idx: int) -> None:
        if self._metrics is not None:
            self._metrics.set_depth(str(idx), len(self._heaps[idx]))

    def _take_token(self) -> float:
        """Global token bucket (reference: 5 rps / burst 10 on prep queues).

        Returns seconds to wait before running (0 if a token was available).
        """
        if self._limiter.global_rps is None:
            return 0.0
        now = time.monotonic()
        self._tokens = min(
            self._limiter.global_burst,
            self._tokens + (now - self._last_refill) * self._limiter.global_rps,
        )
        self._last_refill = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return 0.0
        return (1.0 - self._tokens) / self._limiter.global_rps

    def _hot_delay_locked(self, key: Any) -> float:
        """Fairness escalation for a dirty-requeued key: free re-runs up
        to HOT_THRESHOLD consecutive times, then exponential backoff so
        a tight re-dirty loop cannot starve cold keys on its worker."""
        streak = self._hot.get(key, 0) + 1
        self._hot[key] = streak
        if streak <= self.HOT_THRESHOLD:
            return 0.0
        delay = min(
            self.HOT_BASE_DELAY * (2 ** min(streak - self.HOT_THRESHOLD - 1,
                                            30)),
            self._limiter.max_delay,
        )
        if self._metrics is not None:
            self._metrics.inc_hot_backoff()
        return delay

    def _steal_into_locked(self, idx: int) -> bool:
        """Idle worker ``idx`` claims ready keys from the DEEPEST
        sibling heap (caller holds the shared base lock, i.e. the
        owner's lock). Only due, not-running keys the ``steal``
        predicate admits are eligible; about half of them migrate (the
        owner keeps the rest), preserving per-key serialization --
        a key is in exactly one heap and ``_running`` still excludes
        concurrent re-runs. Returns True when anything was stolen."""
        now = time.monotonic()
        best_idx = -1
        best_ready: list[_Scheduled] = []
        for j, heap in enumerate(self._heaps):
            if j == idx:
                continue
            ready = [
                item for item in heap
                if item.when <= now and item.key not in self._running
                and self._steal(item.key)
            ]
            if len(ready) > len(best_ready):
                best_idx, best_ready = j, ready
        if best_idx < 0 or not best_ready:
            return False
        take = best_ready[-max(1, len(best_ready) // 2):]
        taken = {item.seq for item in take}
        src = self._heaps[best_idx]
        src[:] = [item for item in src if item.seq not in taken]
        heapq.heapify(src)
        for item in take:
            heapq.heappush(self._heaps[idx], item)
        self._observe_depth_locked(best_idx)
        self._observe_depth_locked(idx)
        if self._metrics is not None and \
                hasattr(self._metrics, "inc_steal"):
            self._metrics.inc_steal(len(take))
        if len(best_ready) - len(take) > 1:
            # The victim is still backlogged: cascade the wake to
            # another idle sibling so the whole pool joins the drain.
            for j in self._idle:
                if j != idx and (self._may_steal is None
                                 or self._may_steal(j)):
                    self._worker_cv[j].notify()
                    break
        return True

    def _run(self, idx: int) -> None:
        self._tls.worker = idx
        heap = self._heaps[idx]
        wcv = self._worker_cv[idx]
        while True:
            with self._cv:
                while not self._shutdown and (
                    not heap or heap[0].when > time.monotonic()
                ):
                    if self._steal is not None and (
                            self._may_steal is None
                            or self._may_steal(idx)) and \
                            self._steal_into_locked(idx):
                        continue
                    timeout = None
                    if heap:
                        timeout = max(heap[0].when - time.monotonic(), 0)
                    self._idle.add(idx)
                    try:
                        wcv.wait(timeout=timeout)
                    finally:
                        self._idle.discard(idx)
                if self._shutdown:
                    return
                wait = self._take_token()
                if wait > 0:
                    item = heapq.heappop(heap)
                    item.when = time.monotonic() + wait
                    heapq.heappush(heap, item)
                    continue
                item = heapq.heappop(heap)
                self._size -= 1
                self._running.add(item.key)
                fn = self._fn.get(item.key)
                self._observe_depth_locked(idx)
                self._tls.wait = time.monotonic() - item.born
                if self._metrics is not None:
                    self._metrics.observe_wait(self._tls.wait)
            err: BaseException | None = None
            try:
                if fn is not None:
                    fn(item.key)
            except BaseException as e:  # noqa: BLE001 - retry loop boundary
                err = e
            self._after_run(item.key, err)

    def _after_run(self, key: Any, err: BaseException | None) -> None:
        """Post-callback bookkeeping, shared by the worker loop and
        ``finish`` (batch-taken keys)."""
        if err is None:
            with self._cv:
                self._failures.pop(key, None)
                self._first_failure.pop(key, None)
                self._running.discard(key)
                self._retire_or_requeue_locked(key)
            return
        if isinstance(err, PermanentError):
            self._drop(key, err)
            return
        now = time.monotonic()
        with self._cv:
            first = self._first_failure.setdefault(key, now)
            exhausted = (
                self._limiter.retry_timeout is not None
                and now - first >= self._limiter.retry_timeout
            )
            if not exhausted:
                n = self._failures.get(key, 0) + 1
                self._failures[key] = n
                self._running.discard(key)
                # A retry is scheduled; it looks the callback up
                # at run time, so a fresh fn enqueued mid-flight
                # (or mid-backoff) is picked up automatically.
                self._dirty.discard(key)
                self._push(key, self._limiter.delay_for(n))
                if self._metrics is not None:
                    self._metrics.inc_retry()
        if exhausted:
            logger.warning(
                "%s: retry budget (%.1fs) exhausted for %r",
                self._name, self._limiter.retry_timeout, key,
            )
            self._drop(key, err)
        else:
            logger.warning(
                "%s: %r failed (attempt %d), retrying: %s",
                self._name, key, n, err,
            )

    def _retire_or_requeue_locked(self, key: Any) -> None:
        """Re-push a dirty key (with the fairness escalation delay),
        else retire it from pending. Caller holds the lock."""
        if key in self._dirty and not self._shutdown:
            self._dirty.discard(key)
            # key stays in _pending
            self._push(key, delay=self._hot_delay_locked(key))
        else:
            self._dirty.discard(key)
            self._hot.pop(key, None)
            self._pending.discard(key)
            self._fn.pop(key, None)

    def _drop(self, key: Any, err: BaseException) -> None:
        with self._cv:
            self._failures.pop(key, None)
            self._first_failure.pop(key, None)
            self._running.discard(key)
            self._retire_or_requeue_locked(key)
            if self._metrics is not None:
                self._metrics.inc_drop()
        if self._on_drop:
            self._on_drop(key, err)
        else:
            logger.error("%s: dropping %r: %s", self._name, key, err)
