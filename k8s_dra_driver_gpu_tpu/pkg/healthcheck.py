"""Plugin self-healthcheck: probe our own kubelet-facing sockets.

Reference: cmd/gpu-kubelet-plugin/health.go:51-130 -- a healthcheck
service that dials the plugin's own registration + DRA unix sockets and
reports healthy only when both answer; exposed for container probes.
Here it is a tiny HTTP endpoint (GET /healthz -> 200 ok / 503).
"""

from __future__ import annotations

import grpc

from .dra.proto import plugin_registration_pb2 as regpb
from .dra.service import registration_client_stubs
from .httpserver import SimpleHTTPEndpoint


def probe_sockets(plugin_socket: str, registry_socket: str,
                  timeout: float = 3.0) -> tuple[bool, str]:
    """Dial both sockets like the kubelet would."""
    ch = None
    try:
        ch, get_info, _ = registration_client_stubs(registry_socket)
        info = get_info(regpb.InfoRequest(), timeout=timeout)
        if info.type != "DRAPlugin":
            return False, f"unexpected plugin type {info.type!r}"
    except grpc.RpcError as e:
        return False, f"registration socket: {e.code().name}"
    finally:
        if ch is not None:
            ch.close()
    ch = None
    try:
        # The DRA socket must at least accept a connection.
        ch = grpc.insecure_channel(f"unix://{plugin_socket}")
        grpc.channel_ready_future(ch).result(timeout=timeout)
    except (grpc.RpcError, grpc.FutureTimeoutError):
        return False, "DRA socket not ready"
    finally:
        if ch is not None:
            ch.close()
    return True, "ok"


class HealthcheckServer(SimpleHTTPEndpoint):
    def __init__(self, plugin_socket: str, registry_socket: str,
                 host: str = "127.0.0.1", port: int = 0):
        def handler():
            ok, msg = probe_sockets(plugin_socket, registry_socket)
            return (200 if ok else 503, "text/plain", msg.encode())

        super().__init__("/healthz", handler, host=host, port=port,
                         thread_name="healthcheck")
