"""Plugin self-healthcheck: probe our own kubelet-facing sockets.

Reference: cmd/gpu-kubelet-plugin/health.go:51-130 -- a healthcheck
service that dials the plugin's own registration + DRA unix sockets and
reports healthy only when both answer; exposed for container probes.
Here it is a tiny HTTP endpoint (GET /healthz -> 200 ok / 503).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import grpc

from .dra.proto import plugin_registration_pb2 as regpb
from .dra.service import registration_client_stubs


def probe_sockets(plugin_socket: str, registry_socket: str,
                  timeout: float = 3.0) -> tuple[bool, str]:
    """Dial both sockets like the kubelet would."""
    ch = None
    try:
        ch, get_info, _ = registration_client_stubs(registry_socket)
        info = get_info(regpb.InfoRequest(), timeout=timeout)
        if info.type != "DRAPlugin":
            return False, f"unexpected plugin type {info.type!r}"
    except grpc.RpcError as e:
        return False, f"registration socket: {e.code().name}"
    finally:
        if ch is not None:
            ch.close()
    ch = None
    try:
        # The DRA socket must at least accept a connection.
        ch = grpc.insecure_channel(f"unix://{plugin_socket}")
        grpc.channel_ready_future(ch).result(timeout=timeout)
    except (grpc.RpcError, grpc.FutureTimeoutError):
        return False, "DRA socket not ready"
    finally:
        if ch is not None:
            ch.close()
    return True, "ok"


class HealthcheckServer:
    def __init__(self, plugin_socket: str, registry_socket: str,
                 host: str = "127.0.0.1", port: int = 0):
        plugin_sock, registry_sock = plugin_socket, registry_socket

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path.split("?", 1)[0].rstrip("/") != "/healthz":
                    self.send_response(404)
                    self.end_headers()
                    return
                ok, msg = probe_sockets(plugin_sock, registry_sock)
                body = msg.encode()
                self.send_response(200 if ok else 503)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="healthcheck", daemon=True
        )

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
