"""Validating admission webhook (reference cmd/webhook/)."""
