"""Admission webhook: strict-validate opaque configs at admission time.

Reference: cmd/webhook/main.go -- TLS HTTP server exposing
/validate-resource-claim-parameters (:100); extracts ResourceClaim(
Template)s from an AdmissionReview across resource.k8s.io v1/v1beta1/
v1beta2 (resource.go:33-150), strict-decodes any driver-owned opaque
config and runs Normalize()+Validate(). Optional -- the same strict
decoding re-runs at Prepare time.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import ssl
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..api.decode import DecodeError, strict_decode
from ..api.configs import ValidationError

logger = logging.getLogger(__name__)

VALIDATE_PATH = "/validate-resource-claim-parameters"
OUR_DRIVERS = ("tpu.dra.dev", "compute-domain.tpu.dra.dev")
SUPPORTED_VERSIONS = ("v1", "v1beta1", "v1beta2")


def extract_device_configs(obj: dict) -> list[dict]:
    """Opaque parameter objects owned by our drivers, from a
    ResourceClaim or ResourceClaimTemplate (resource.go:82-150)."""
    kind = obj.get("kind", "")
    if kind == "ResourceClaimTemplate":
        spec = obj.get("spec", {}).get("spec", {})
    else:
        spec = obj.get("spec", {})
    out = []
    for entry in spec.get("devices", {}).get("config", []):
        opaque = entry.get("opaque") or {}
        if opaque.get("driver") in OUR_DRIVERS:
            out.append(opaque.get("parameters", {}))
    return out


def validate_admission_review(review: dict) -> dict:
    """AdmissionReview in -> AdmissionReview out with allowed verdict."""
    request = review.get("request") or {}
    uid = request.get("uid", "")
    response: dict = {"uid": uid, "allowed": True}

    obj = request.get("object") or {}
    api_version = obj.get("apiVersion", "")
    group_version = api_version.rsplit("/", 1)[-1] if api_version else ""
    if (
        obj.get("kind") in ("ResourceClaim", "ResourceClaimTemplate")
        and group_version in SUPPORTED_VERSIONS
    ):
        for params in extract_device_configs(obj):
            try:
                cfg = strict_decode(params)
                cfg.normalize()
                cfg.validate()
            except (DecodeError, ValidationError) as e:
                response["allowed"] = False
                response["status"] = {
                    "message": f"invalid device config: {e}",
                    "code": 422,
                }
                break
    elif obj.get("kind") == "ComputeDomain":
        # Fail fast at admission what would otherwise surface as a
        # PermanentError in every node's channel prepare: a
        # cross-slice domain must split its hosts evenly over slices.
        from ..computedomain import per_slice_workers  # noqa: PLC0415

        try:
            per_slice_workers(obj.get("spec") or {})
        except ValueError as e:
            response["allowed"] = False
            response["status"] = {"message": str(e), "code": 422}
    return {
        "apiVersion": review.get(
            "apiVersion", "admission.k8s.io/v1"
        ),
        "kind": "AdmissionReview",
        "response": response,
    }


class _Handler(BaseHTTPRequestHandler):
    def do_POST(self):  # noqa: N802 - http.server API
        if self.path.split("?", 1)[0] != VALIDATE_PATH:
            self.send_response(404)
            self.end_headers()
            return
        length = int(self.headers.get("Content-Length", "0"))
        try:
            review = json.loads(self.rfile.read(length))
            out = validate_admission_review(review)
        except (json.JSONDecodeError, AttributeError) as e:
            self.send_response(400)
            self.end_headers()
            self.wfile.write(str(e).encode())
            return
        body = json.dumps(out).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):
        pass


class WebhookServer:
    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 0,
        tls_cert: str | None = None,
        tls_key: str | None = None,
    ):
        self._server = ThreadingHTTPServer((host, port), _Handler)
        if tls_cert and tls_key:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert, tls_key)
            self._server.socket = ctx.wrap_socket(
                self._server.socket, server_side=True
            )
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="webhook", daemon=True
        )

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpu-dra-webhook")
    p.add_argument("--port", type=int, default=8443)
    p.add_argument("--tls-cert")
    p.add_argument("--tls-key")
    p.add_argument("-v", "--verbosity", type=int,
                   default=int(os.environ.get("V", "4")),
                   help="log verbosity (see pkg/logsetup.py) [V]")
    return p


def main(argv: list[str] | None = None) -> int:
    from .. import __version__  # noqa: PLC0415
    from ..pkg import logsetup  # noqa: PLC0415

    args = build_parser().parse_args(argv)
    logsetup.setup(args.verbosity)
    logsetup.log_startup(__name__, "tpu-dra-webhook", __version__, args)
    server = WebhookServer(port=args.port, tls_cert=args.tls_cert,
                           tls_key=args.tls_key)
    server.start()
    logsetup.startup_logger(__name__).info(
        "webhook serving on :%d%s", server.port, VALIDATE_PATH)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
