"""Webhook TLS bootstrap: self-signed cert -> Secret + caBundle patch.

Reference: the reference chart automates webhook TLS (cert-manager
issuer or a generated secret, deployments/helm/.../webhook-cert-*.yaml).
This is the generated-secret path as an in-tree tool the chart runs as a
post-install Job (no cert-manager, no kubectl, no helm crypto needed):

1. Generate a self-signed CA + server certificate for
   ``<service>.<namespace>.svc`` with openssl.
2. Create/update the TLS Secret the webhook Deployment mounts.
3. Patch the ValidatingWebhookConfiguration's clientConfig.caBundle so
   the API server trusts it.

Idempotent: an existing, still-valid Secret is kept (only the caBundle
patch is re-applied from it), so rollouts don't churn serving certs.
"""

from __future__ import annotations

import argparse
import base64
import logging
import os
import subprocess
import sys
import tempfile

from ..pkg.kubeclient import ConflictError, KubeClient, NotFoundError

logger = logging.getLogger(__name__)


def generate_self_signed(service: str, namespace: str,
                         days: int = 3650) -> tuple[bytes, bytes]:
    """(cert_pem, key_pem) for the service DNS names, via openssl."""
    cn = f"{service}.{namespace}.svc"
    sans = f"DNS:{cn},DNS:{cn}.cluster.local,DNS:{service}.{namespace}"
    with tempfile.TemporaryDirectory() as d:
        cert = os.path.join(d, "tls.crt")
        key = os.path.join(d, "tls.key")
        subprocess.run(
            [
                "openssl", "req", "-x509", "-newkey", "rsa:2048",
                "-keyout", key, "-out", cert,
                "-days", str(days), "-nodes",
                "-subj", f"/CN={cn}",
                "-addext", f"subjectAltName={sans}",
            ],
            check=True, capture_output=True,
        )
        with open(cert, "rb") as f:
            cert_pem = f.read()
        with open(key, "rb") as f:
            key_pem = f.read()
    return cert_pem, key_pem


def cert_valid(cert_pem: bytes, service: str, namespace: str,
               min_remaining_s: int = 30 * 24 * 3600) -> bool:
    """The cert must carry the service DNS name as a subjectAltName and
    not expire within ``min_remaining_s`` -- otherwise the bootstrap
    regenerates it instead of re-trusting a stale Secret forever.

    The SAN extension specifically: API servers ignore the Subject CN,
    so a CN-only cert (e.g. an externally created Secret) would keep the
    webhook broken forever if we accepted it."""
    try:
        check = subprocess.run(
            ["openssl", "x509", "-noout", "-checkend",
             str(min_remaining_s)],
            input=cert_pem, capture_output=True,
        )
        if check.returncode != 0:
            return False
        san = subprocess.run(
            ["openssl", "x509", "-noout", "-ext", "subjectAltName"],
            input=cert_pem, capture_output=True, check=True,
        ).stdout.decode()
    except (OSError, subprocess.SubprocessError):
        return False
    dns_names = {
        entry.strip()[len("DNS:"):]
        for entry in san.replace("\n", ",").split(",")
        if entry.strip().startswith("DNS:")
    }
    return f"{service}.{namespace}.svc" in dns_names


def ensure_secret(kube, name: str, namespace: str, service: str) -> bytes:
    """Create (or refresh) the TLS secret; returns the PEM cert (CA ==
    server cert for the self-signed case). An existing STILL-VALID
    secret is kept so rollouts don't churn serving certs; an expired or
    wrong-SAN one is replaced."""
    existing = None
    try:
        existing = kube.get("", "v1", "secrets", name, namespace=namespace)
        cert_b64 = existing.get("data", {}).get("tls.crt", "")
        if cert_b64:
            cert = base64.b64decode(cert_b64)
            if cert_valid(cert, service, namespace):
                logger.info("secret %s/%s valid; keeping it",
                            namespace, name)
                return cert
            logger.warning("secret %s/%s invalid/expiring; regenerating",
                           namespace, name)
    except NotFoundError:
        pass
    cert_pem, key_pem = generate_self_signed(service, namespace)
    secret = {
        "apiVersion": "v1",
        "kind": "Secret",
        "type": "kubernetes.io/tls",
        "metadata": {"name": name, "namespace": namespace},
        "data": {
            "tls.crt": base64.b64encode(cert_pem).decode(),
            "tls.key": base64.b64encode(key_pem).decode(),
            "ca.crt": base64.b64encode(cert_pem).decode(),
        },
    }
    if existing is not None:
        kube.update("", "v1", "secrets", name, secret, namespace=namespace)
        logger.info("replaced secret %s/%s", namespace, name)
        return cert_pem
    try:
        kube.create("", "v1", "secrets", secret, namespace=namespace)
        logger.info("created secret %s/%s", namespace, name)
    except ConflictError:  # racing replica created it first
        existing = kube.get("", "v1", "secrets", name, namespace=namespace)
        return base64.b64decode(existing["data"]["tls.crt"])
    return cert_pem


def patch_ca_bundle(kube, webhook_config: str, ca_pem: bytes) -> None:
    from ..pkg import json_copy  # noqa: PLC0415 - leaf helper

    # Deep-copy before mutating the fetched config (TPUDRA006).
    obj = json_copy(kube.get("admissionregistration.k8s.io", "v1",
                             "validatingwebhookconfigurations",
                             webhook_config))
    for wh in obj.get("webhooks", []):
        wh.setdefault("clientConfig", {})["caBundle"] = base64.b64encode(
            ca_pem).decode()
    kube.update("admissionregistration.k8s.io", "v1",
                "validatingwebhookconfigurations", webhook_config, obj)
    logger.info("patched caBundle on %s", webhook_config)


def run(kube, service: str, namespace: str, secret_name: str,
        webhook_config: str, mode: str = "both") -> int:
    """mode: "create" (pre-install: Secret only -- the webhook config
    doesn't exist yet), "patch" (post-install: caBundle only), or
    "both" (manual/one-shot)."""
    ca_pem = ensure_secret(kube, secret_name, namespace, service)
    if mode != "create":
        patch_ca_bundle(kube, webhook_config, ca_pem)
    return 0


def build_parser() -> argparse.ArgumentParser:
    env = os.environ.get
    p = argparse.ArgumentParser(prog="tpu-dra-webhook-certbootstrap")
    p.add_argument("--service", default=env("WEBHOOK_SERVICE",
                                            "tpu-dra-webhook"))
    p.add_argument("--namespace", default=env("DRIVER_NAMESPACE",
                                              "tpu-dra-driver"))
    p.add_argument("--secret-name", default=env("TLS_SECRET_NAME",
                                                "tpu-dra-webhook-tls"))
    p.add_argument("--webhook-config", default=env("WEBHOOK_CONFIG",
                                                   "tpu-dra-webhook"))
    p.add_argument("--mode", choices=["create", "patch", "both"],
                   default=env("CERT_BOOTSTRAP_MODE", "both"))
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    from ..pkg.retry import RetryingKubeClient  # noqa: PLC0415

    return run(RetryingKubeClient(KubeClient()), args.service,
               args.namespace, args.secret_name, args.webhook_config,
               mode=args.mode)


if __name__ == "__main__":
    sys.exit(main())
