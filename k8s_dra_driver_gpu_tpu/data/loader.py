"""Sharded, deterministic, resumable token-stream loading.

The reference ships no data path at all (its workloads are external);
gang training needs one with three properties this module provides:

1. **Host-sharded**: each process of the gang reads a disjoint slice of
   every global batch, keyed by the SAME env contract the driver
   injects (TPU_PROCESS_ID / TPU_NUM_PROCESSES) -- no coordination
   traffic for data.
2. **Deterministic + resumable**: batch(step) is a pure function of
   (file, config, step), so resuming from an orbax checkpoint at step N
   replays exactly the batches N, N+1, ... with zero loader state to
   checkpoint.
3. **Zero-copy**: token files are np.memmap'd; a batch is a strided
   gather, no epoch shuffling buffers (shuffling = a multiplicative
   congruential permutation over sequence slots, O(1) memory).
"""

from __future__ import annotations

import os

import numpy as np

TOKEN_DTYPES = {"uint16": np.uint16, "uint32": np.uint32, "int32": np.int32}


def write_token_file(path: str, tokens, dtype: str = "uint16") -> None:
    """Helper for tests/preprocessing: dump a 1-D token array.

    Validates range before casting: a silent wrap (e.g. llama3 ids
    >= 65536 into uint16) would produce VALID-looking garbage tokens
    no downstream vocab check could catch."""
    arr = np.asarray(tokens)
    info = np.iinfo(TOKEN_DTYPES[dtype])
    lo, hi = int(arr.min()), int(arr.max())
    if lo < info.min or hi > info.max:
        raise ValueError(
            f"token ids [{lo}, {hi}] don't fit dtype {dtype} "
            f"[{info.min}, {info.max}]"
        )
    arr.astype(TOKEN_DTYPES[dtype]).tofile(path)


class TokenDataset:
    """A flat token stream on disk, viewed as fixed-length sequences."""

    def __init__(self, path: str, seq_len: int, dtype: str = "uint16"):
        self.path = path
        self.seq_len = seq_len
        self._tokens = np.memmap(path, dtype=TOKEN_DTYPES[dtype], mode="r")
        # +1: each sample is seq_len inputs + 1 shifted target.
        self.num_sequences = (len(self._tokens) - 1) // seq_len
        if self.num_sequences <= 0:
            raise ValueError(
                f"{path}: {len(self._tokens)} tokens < one sequence of "
                f"{seq_len}+1"
            )

    def sequence(self, index: int) -> np.ndarray:
        """-> [seq_len + 1] tokens (inputs + next-token targets)."""
        start = index * self.seq_len
        return np.asarray(self._tokens[start:start + self.seq_len + 1])

    def max_token(self) -> int:
        """Largest token id in the file, cached in a sidecar keyed by
        (size, mtime) so preemption-resume doesn't rescan a huge file."""
        import json  # noqa: PLC0415

        st = os.stat(self.path)
        key = [st.st_size, int(st.st_mtime)]
        sidecar = self.path + ".max.json"
        try:
            with open(sidecar, encoding="utf-8") as f:
                doc = json.load(f)
            if doc.get("key") == key:
                return int(doc["max"])
        except (OSError, ValueError, KeyError):
            pass
        value = int(self._tokens.max())
        try:
            with open(sidecar, "w", encoding="utf-8") as f:
                json.dump({"key": key, "max": value}, f)
        except OSError:
            pass  # cache is best-effort
        return value


def _permute(index: np.ndarray, n: int, seed: int) -> np.ndarray:
    """Stateless pseudo-random permutation of [0, n): an affine map with
    a multiplier coprime to n (Weyl-style). Deterministic, O(1) memory."""
    rng = np.random.RandomState(seed)
    a = int(rng.randint(1, max(n, 2)))
    while np.gcd(a, n) != 1:
        a += 1
    b = int(rng.randint(0, max(n, 1)))
    return (index * a + b) % n


class ShardedBatchIterator:
    """batch(step) for one gang member.

    Global batch `global_batch` splits evenly over `num_shards`; this
    shard materializes only its `global_batch // num_shards` rows.
    """

    def __init__(
        self,
        dataset: TokenDataset,
        global_batch: int,
        num_shards: int | None = None,
        shard_id: int | None = None,
        seed: int = 0,
        env=os.environ,
    ):
        self.ds = dataset
        if num_shards is None:
            num_shards = int(env.get("TPU_NUM_PROCESSES", "1"))
        if shard_id is None:
            shard_id = int(env.get("TPU_PROCESS_ID", "0"))
        if global_batch % num_shards:
            raise ValueError(
                f"global batch {global_batch} not divisible by "
                f"{num_shards} shards"
            )
        if not 0 <= shard_id < num_shards:
            raise ValueError(f"shard_id {shard_id} not in [0, {num_shards})")
        if dataset.num_sequences < global_batch:
            # A permutation over fewer slots than one global batch could
            # not keep the shards' rows disjoint.
            raise ValueError(
                f"dataset has {dataset.num_sequences} sequences < one "
                f"global batch of {global_batch}"
            )
        self.global_batch = global_batch
        self.local_batch = global_batch // num_shards
        self.num_shards = num_shards
        self.shard_id = shard_id
        self.seed = seed
        self.steps_per_epoch = max(self.ds.num_sequences // global_batch, 1)

    def batch(self, step: int) -> np.ndarray:
        """-> [local_batch, seq_len + 1] int32 tokens for ``step``."""
        epoch = step // self.steps_per_epoch
        pos = step % self.steps_per_epoch
        row0 = pos * self.global_batch + self.shard_id * self.local_batch
        slots = np.arange(row0, row0 + self.local_batch)
        # Permute over the WHOLE dataset (not just the consumed prefix):
        # each epoch's distinct affine map rotates which tail sequences
        # fall off the drop-last edge, so every sample is eventually
        # seen. Injectivity over [0, num_sequences) keeps shards
        # disjoint within a step.
        slots = _permute(slots, self.ds.num_sequences, self.seed + epoch)
        return np.stack(
            [self.ds.sequence(int(s)) for s in slots]
        ).astype(np.int32)
