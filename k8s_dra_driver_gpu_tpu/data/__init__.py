"""Data loading for gang-scheduled training jobs."""
