/* tpuinfo: TPU device enumeration library (C API).
 *
 * This is the TPU-native replacement for the reference driver's NVML
 * dependency (reference: cmd/gpu-kubelet-plugin/nvlib.go loads
 * libnvidia-ml.so.1 via cgo). Instead of GPU UUID/MIG queries it reports
 * TPU chips with ICI coordinates, slice topology, HBM capacity and
 * TensorCore counts, and enumerates valid sub-slice carve-out profiles
 * (the MIG-profile analog).
 *
 * All functions returning char* return a malloc'd NUL-terminated JSON
 * document the caller must release with tpuinfo_free(). Options are
 * passed as a "key=value;key=value" string; recognized keys:
 *   mock_topology   e.g. "v5p-16" - use a built-in mock profile instead
 *                   of probing the host (mirrors the reference's mock
 *                   NVML, hack/ci/mock-nvml/).
 *   worker_id       which host of a multi-host slice this is (default 0).
 *   dev_root        device directory to probe (default "/dev").
 *   sys_root        sysfs root to probe (default "/sys").
 *   health_events   injected mock health events, '|'-separated (';' is
 *                   the options separator), format
 *                   "chip=1,kind=hbm_uncorrectable|chip=2,kind=ici_link_down".
 */

#ifndef TPUINFO_H_
#define TPUINFO_H_

#ifdef __cplusplus
extern "C" {
#endif

/* Library version, "major.minor.patch". Static string; do not free. */
const char* tpuinfo_version(void);

/* Enumerate the chips visible on this host.
 *
 * JSON shape:
 * {
 *   "platform": "v5p",            // generation: v4|v5e|v5p|v6e
 *   "accelerator_type": "v5p-16", // slice name if known, else ""
 *   "topology": "2x2x2",          // chip-grid dims of the full slice
 *   "num_slice_chips": 8,         // chips in the full slice
 *   "num_hosts": 2,
 *   "worker_id": 0,
 *   "chips_per_host": 4,
 *   "cores_per_chip": 2,
 *   "hbm_bytes_per_chip": 102005473280,
 *   "chips": [
 *     {"index":0, "uuid":"tpu-v5p-16-w0-c0", "devpath":"/dev/accel0",
 *      "ici_coords":[0,0,0], "numa_node":0, "pci_bdf":"0000:00:04.0",
 *      "healthy": true}
 *   ],
 *   "source": "mock"              // mock|devfs|none
 * }
 */
char* tpuinfo_enumerate(const char* opts);

/* Enumerate valid sub-slice carve-out profiles for one host's chips
 * (the MIG GI/CI-profile analog; reference nvlib.go
 * inspectMigProfilesAndPlacements).
 *
 * JSON shape:
 * {
 *   "profiles": [
 *     {"name":"1c", "chips":0, "cores":1, "placements":[0,1,...,7],
 *      "hbm_bytes": 51002736640},   // half-chip (single TensorCore)
 *     {"name":"1x1", "chips":1, "cores":2, "placements":[0,1,2,3], ...},
 *     {"name":"2x1", "chips":2, "cores":4, "placements":[0,2], ...},
 *     {"name":"2x2", "chips":4, "cores":8, "placements":[0], ...}
 *   ]
 * }
 * Placement units: for core profiles ("Nc") the placement is a core
 * index; for chip profiles the placement is the starting chip index of a
 * contiguous aligned block in the host's chip grid.
 */
char* tpuinfo_subslice_profiles(const char* opts);

/* Read per-chip health. JSON: {"events":[{"chip":1,"kind":"...",
 * "fatal":true}]} - empty events list when healthy. */
char* tpuinfo_health(const char* opts);

void tpuinfo_free(char* p);

#ifdef __cplusplus
}
#endif

#endif /* TPUINFO_H_ */
