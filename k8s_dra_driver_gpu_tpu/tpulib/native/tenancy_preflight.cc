// Tenancy preflight: CDI createContainer/poststop OCI hook (native).
//
// The container runtime executes CDI hooks on the HOST, so this must be
// a self-contained binary with no interpreter dependency -- the analog
// of nvidia-cdi-hook, which the reference copies into the plugin dir on
// the host at startup (gpu main.go:293). The kubelet plugin copies this
// binary into <state-root>/bin/ (a hostPath) and the claim's CDI spec
// points its hooks here.
//
// createContainer: REGISTER <id> <hbm> with the claim's tenancy agent;
// a DENIED reply (over max-clients / over HBM budget) exits 1 and the
// runtime refuses to start the container. poststop: RELEASE <id> so a
// restarted container (new OCI id) does not leak its admission slot.
// The container id comes from the OCI state JSON on stdin.
//
// Build: static-linked (see Makefile) so it runs on minimal host images
// (COS) that ship neither python nor a matching libstdc++.

#include <fcntl.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/time.h>
#include <sys/un.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <string>

namespace {

// Minimal extraction of "id":"..." from the OCI state JSON on stdin.
std::string StateId() {
  std::string input;
  char buf[4096];
  ssize_t n;
  while ((n = read(STDIN_FILENO, buf, sizeof(buf))) > 0) {
    input.append(buf, static_cast<size_t>(n));
    if (input.size() > 1 << 20) break;  // state blobs are small
  }
  size_t key = input.find("\"id\"");
  if (key == std::string::npos) return "";
  size_t colon = input.find(':', key);
  if (colon == std::string::npos) return "";
  size_t open = input.find('"', colon);
  if (open == std::string::npos) return "";
  size_t close = input.find('"', open + 1);
  if (close == std::string::npos) return "";
  return input.substr(open + 1, close - open - 1);
}

int Query(const std::string& sock_path, const std::string& request,
          std::string* reply) {
  int fd = socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  // A wedged-but-listening agent must not hang container creation:
  // bound every socket op (connect honors SO_SNDTIMEO on Linux). The
  // CDI hook entry also carries its own runtime-enforced timeout.
  timeval tv{5, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  sockaddr_un addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sun_family = AF_UNIX;
  if (sock_path.size() >= sizeof(addr.sun_path)) {
    close(fd);
    return -1;
  }
  std::strncpy(addr.sun_path, sock_path.c_str(), sizeof(addr.sun_path) - 1);
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    close(fd);
    return -1;
  }
  std::string line = request + "\n";
  if (write(fd, line.c_str(), line.size()) < 0) {
    close(fd);
    return -1;
  }
  char buf[4096];
  ssize_t n;
  while ((n = read(fd, buf, sizeof(buf))) > 0) {
    reply->append(buf, static_cast<size_t>(n));
    if (!reply->empty() && reply->back() == '\n') break;
  }
  close(fd);
  while (!reply->empty() &&
         (reply->back() == '\n' || reply->back() == '\r')) {
    reply->pop_back();
  }
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  std::string dir, hbm = "0", client;
  bool release = false;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    if (a == "--dir" && i + 1 < argc) dir = argv[++i];
    else if (a == "--hbm-bytes" && i + 1 < argc) hbm = argv[++i];
    else if (a == "--client-id" && i + 1 < argc) client = argv[++i];
    else if (a == "--release") release = true;
  }
  if (dir.empty()) {
    std::fprintf(stderr, "tenancy-preflight: --dir required\n");
    return 1;
  }
  if (client.empty()) client = StateId();
  if (client.empty() || client.find('/') != std::string::npos ||
      client == "." || client == "..") {
    std::fprintf(stderr, "tenancy-preflight: no usable client identity\n");
    // poststop must not fail the runtime's teardown path.
    return release ? 0 : 1;
  }
  std::string request = release ? "RELEASE " + client
                                : "REGISTER " + client + " " + hbm;
  std::string reply;
  if (Query(dir + "/agent.sock", request, &reply) != 0) {
    std::fprintf(stderr, "tenancy-preflight: agent unreachable at %s\n",
                 dir.c_str());
    if (release) {
      // Tombstone: the agent reclaims this slot from released.d before
      // its next admission, so a lost RELEASE never leaks permanently.
      std::string rd = dir + "/released.d";
      mkdir(rd.c_str(), 0755);
      int tfd = open((rd + "/" + client).c_str(),
                     O_WRONLY | O_CREAT | O_TRUNC, 0644);
      if (tfd >= 0) close(tfd);
      return 0;  // never block container teardown
    }
    return 1;  // fail closed on admission
  }
  if (release || reply.rfind("OK", 0) == 0) return 0;
  std::fprintf(stderr, "tenancy-preflight: %s\n", reply.c_str());
  return 1;
}
