// tpuinfo: TPU device enumeration library.
//
// TPU-native replacement for the reference's NVML layer (reference:
// cmd/gpu-kubelet-plugin/nvlib.go). See tpuinfo.h for the C API contract.
//
// Two backends behind one interface, selected per call:
//   - mock: built-in slice profiles (v4/v5e/v5p/v6e), mirroring the
//     reference's mock-NVML test strategy (hack/ci/mock-nvml/) so the whole
//     claim->prepare->CDI pipeline runs on CPU-only hosts.
//   - devfs: probe /dev/accel* + sysfs on a real TPU VM.

#include "tpuinfo.h"

#include <dirent.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <cctype>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <map>
#include <sstream>
#include <string>
#include <vector>

namespace {

constexpr const char* kVersion = "0.1.0";

// ---------------------------------------------------------------------------
// Options: "key=value;key=value"
// ---------------------------------------------------------------------------

std::map<std::string, std::string> ParseOpts(const char* opts) {
  std::map<std::string, std::string> out;
  if (opts == nullptr) return out;
  std::stringstream ss(opts);
  std::string item;
  while (std::getline(ss, item, ';')) {
    auto eq = item.find('=');
    if (eq == std::string::npos) continue;
    out[item.substr(0, eq)] = item.substr(eq + 1);
  }
  return out;
}

std::string Opt(const std::map<std::string, std::string>& o, const char* k,
                const std::string& dflt = "") {
  auto it = o.find(k);
  return it == o.end() ? dflt : it->second;
}

// ---------------------------------------------------------------------------
// Generation + topology database
// ---------------------------------------------------------------------------

struct Generation {
  const char* name;
  int dims;            // 2 (mesh) or 3 (torus)
  int chips_per_host;  // chips managed by one CPU host
  int cores_per_chip;  // TensorCores per chip (2 = megacore-capable)
  long long hbm_bytes; // per chip
  // Accelerator-type suffix counts cores (v4/v5p) or chips (v5e/v6e).
  bool type_counts_cores;
};

const Generation kGenerations[] = {
    {"v4", 3, 4, 2, 32LL << 30, true},
    {"v5e", 2, 4, 1, 16LL << 30, false},
    {"v5p", 3, 4, 2, 95LL << 30, true},
    {"v6e", 2, 4, 1, 32LL << 30, false},
};

const Generation* FindGeneration(const std::string& name) {
  for (const auto& g : kGenerations) {
    if (name == g.name) return &g;
  }
  return nullptr;
}

struct Shape {
  int x = 1, y = 1, z = 1;
  int count() const { return x * y * z; }
  std::string str(int dims) const {
    char buf[48];
    if (dims == 2) {
      std::snprintf(buf, sizeof(buf), "%dx%d", x, y);
    } else {
      std::snprintf(buf, sizeof(buf), "%dx%dx%d", x, y, z);
    }
    return buf;
  }
};

// Standard slice shapes per chip count (chips, not cores).
// 3D torus shapes follow v4/v5p slice geometry; 2D mesh shapes follow
// v5e/v6e pod geometry.
Shape SliceShape(const Generation& g, int chips) {
  static const std::map<int, Shape> k3d = {
      {1, {1, 1, 1}},  {2, {1, 1, 2}},   {4, {2, 2, 1}},   {8, {2, 2, 2}},
      {16, {2, 2, 4}}, {32, {2, 4, 4}},  {64, {4, 4, 4}},  {128, {4, 4, 8}},
      {256, {4, 8, 8}}, {512, {8, 8, 8}},
  };
  static const std::map<int, Shape> k2d = {
      {1, {1, 1, 1}},  {2, {1, 2, 1}},  {4, {2, 2, 1}},   {8, {2, 4, 1}},
      {16, {4, 4, 1}}, {32, {4, 8, 1}}, {64, {8, 8, 1}},  {128, {8, 16, 1}},
      {256, {16, 16, 1}},
  };
  const auto& tbl = g.dims == 3 ? k3d : k2d;
  auto it = tbl.find(chips);
  if (it != tbl.end()) return it->second;
  // Fallback: flat line along y (keeps enumeration well-defined for
  // non-standard mock sizes).
  Shape s;
  s.y = chips;
  return s;
}

// The chip block one host owns within the slice grid.
Shape HostShape(const Generation& g) {
  if (g.chips_per_host == 8) return {2, 4, 1};
  if (g.chips_per_host == 4) return {2, 2, 1};
  if (g.chips_per_host == 2) return {1, 2, 1};
  return {1, 1, 1};
}

// Parse "v5p-16" / "v5e-4" into (generation, chips). Strict: the suffix
// must be all digits (parity with the Python backend's fullmatch).
bool ParseAcceleratorType(const std::string& t, const Generation** gen,
                          int* chips) {
  auto dash = t.find('-');
  if (dash == std::string::npos || dash + 1 >= t.size()) return false;
  const Generation* g = FindGeneration(t.substr(0, dash));
  if (g == nullptr) return false;
  for (size_t i = dash + 1; i < t.size(); i++) {
    if (!std::isdigit(static_cast<unsigned char>(t[i]))) return false;
  }
  int n = std::atoi(t.c_str() + dash + 1);
  if (n <= 0) return false;
  *gen = g;
  *chips = g->type_counts_cores ? n / g->cores_per_chip : n;
  return *chips > 0;
}

// ---------------------------------------------------------------------------
// Minimal JSON emission
// ---------------------------------------------------------------------------

class Json {
 public:
  Json& raw(const std::string& s) {
    out_ += s;
    return *this;
  }
  Json& str(const std::string& s) {
    out_ += '"';
    for (char c : s) {
      switch (c) {
        case '"': out_ += "\\\""; break;
        case '\\': out_ += "\\\\"; break;
        case '\n': out_ += "\\n"; break;
        default:
          if (static_cast<unsigned char>(c) < 0x20) {
            char buf[8];
            std::snprintf(buf, sizeof(buf), "\\u%04x", c);
            out_ += buf;
          } else {
            out_ += c;
          }
      }
    }
    out_ += '"';
    return *this;
  }
  Json& num(long long v) {
    out_ += std::to_string(v);
    return *this;
  }
  Json& boolean(bool b) {
    out_ += b ? "true" : "false";
    return *this;
  }
  char* release() {
    char* p = static_cast<char*>(std::malloc(out_.size() + 1));
    std::memcpy(p, out_.c_str(), out_.size() + 1);
    return p;
  }

 private:
  std::string out_;
};

// ---------------------------------------------------------------------------
// Chip model
// ---------------------------------------------------------------------------

struct Chip {
  int index = 0;
  std::string uuid;
  std::string devpath;
  int coords[3] = {0, 0, 0};
  int numa_node = -1;
  std::string pci_bdf;
  bool healthy = true;
};

struct HostInfo {
  const Generation* gen = nullptr;
  std::string accelerator_type;
  Shape slice;
  int num_hosts = 1;
  int worker_id = 0;
  std::vector<Chip> chips;
  std::string source;
};

// ICI coordinates of local chip `local` on worker `worker`: hosts tile the
// slice grid in row-major host-block order (x fastest), chips tile the
// host block the same way.
void ChipCoords(const Shape& slice, const Shape& host, int worker, int local,
                int out[3]) {
  int bx = slice.x / host.x, by = slice.y / host.y;
  if (bx < 1) bx = 1;
  if (by < 1) by = 1;
  int wx = worker % bx;
  int wy = (worker / bx) % by;
  int wz = worker / (bx * by);
  int lx = local % host.x;
  int ly = (local / host.x) % host.y;
  int lz = local / (host.x * host.y);
  out[0] = wx * host.x + lx;
  out[1] = wy * host.y + ly;
  out[2] = wz * host.z + lz;
}

HostInfo MockEnumerate(const std::map<std::string, std::string>& opts) {
  HostInfo h;
  h.source = "mock";
  std::string type = Opt(opts, "mock_topology", "v5e-4");
  int chips = 0;
  if (!ParseAcceleratorType(type, &h.gen, &chips)) {
    h.gen = FindGeneration("v5e");
    chips = 4;
    type = "v5e-4";  // fall back wholesale so derived UUIDs match too
  }
  h.accelerator_type = type;
  h.slice = SliceShape(*h.gen, chips);
  Shape host = HostShape(*h.gen);
  int per_host = std::min(chips, h.gen->chips_per_host);
  // A host owning fewer chips than a full block covers the (smaller)
  // slice grid itself; keep coords inside that grid.
  if (per_host < host.count()) host = SliceShape(*h.gen, per_host);
  h.num_hosts = (chips + h.gen->chips_per_host - 1) / h.gen->chips_per_host;
  h.worker_id = std::atoi(Opt(opts, "worker_id", "0").c_str());
  for (int i = 0; i < per_host; i++) {
    Chip c;
    c.index = i;
    char buf[128];
    std::snprintf(buf, sizeof(buf), "tpu-%s-w%d-c%d", type.c_str(),
                  h.worker_id, i);
    c.uuid = buf;
    std::snprintf(buf, sizeof(buf), "/dev/accel%d", i);
    c.devpath = buf;
    std::snprintf(buf, sizeof(buf), "0000:00:%02x.0", 4 + i);
    c.pci_bdf = buf;
    c.numa_node = i < per_host / 2 ? 0 : (per_host > 1 ? 1 : 0);
    ChipCoords(h.slice, host, h.worker_id, i, c.coords);
    h.chips.push_back(c);
  }
  return h;
}

std::string ReadFileTrim(const std::string& path) {
  std::ifstream f(path);
  if (!f) return "";
  std::string s;
  std::getline(f, s);
  while (!s.empty() && (s.back() == '\n' || s.back() == '\r')) s.pop_back();
  return s;
}

HostInfo DevfsEnumerate(const std::map<std::string, std::string>& opts) {
  HostInfo h;
  const std::string dev_root = Opt(opts, "dev_root", "/dev");
  const std::string sys_root = Opt(opts, "sys_root", "/sys");

  // Generation from the environment the TPU runtime publishes on GKE/GCE
  // TPU VMs; fall back to v5e when undetectable.
  const char* type_env = std::getenv("TPU_ACCELERATOR_TYPE");
  int slice_chips = 0;
  if (type_env == nullptr ||
      !ParseAcceleratorType(type_env, &h.gen, &slice_chips)) {
    h.gen = FindGeneration("v5e");
    h.accelerator_type = "";
  } else {
    h.accelerator_type = type_env;
  }

  DIR* d = opendir(dev_root.c_str());
  std::vector<int> indices;
  if (d != nullptr) {
    while (dirent* e = readdir(d)) {
      // Strict "accel<digits>" match: reject trailing junk and negatives
      // (keeps enumeration identical to the Python backend's fullmatch).
      int idx;
      char extra;
      if (std::sscanf(e->d_name, "accel%d%c", &idx, &extra) == 1 && idx >= 0 &&
          std::isdigit(static_cast<unsigned char>(e->d_name[5]))) {
        indices.push_back(idx);
      }
    }
    closedir(d);
  }
  std::sort(indices.begin(), indices.end());
  h.source = indices.empty() ? "none" : "devfs";
  if (slice_chips == 0) slice_chips = static_cast<int>(indices.size());
  if (slice_chips == 0) slice_chips = 1;
  h.slice = SliceShape(*h.gen, slice_chips);
  h.num_hosts =
      (slice_chips + h.gen->chips_per_host - 1) / h.gen->chips_per_host;
  const char* wid = std::getenv("TPU_WORKER_ID");
  h.worker_id = wid != nullptr ? std::atoi(wid) : 0;
  Shape host = HostShape(*h.gen);
  if (!indices.empty() && static_cast<int>(indices.size()) < host.count()) {
    host = SliceShape(*h.gen, static_cast<int>(indices.size()));
  }
  for (size_t pos = 0; pos < indices.size(); pos++) {
    int idx = indices[pos];
    Chip c;
    c.index = idx;
    c.devpath = dev_root + "/accel" + std::to_string(idx);
    std::string sysdev =
        sys_root + "/class/accel/accel" + std::to_string(idx) + "/device";
    std::string numa = ReadFileTrim(sysdev + "/numa_node");
    c.numa_node = numa.empty() ? -1 : std::atoi(numa.c_str());
    // The device symlink's basename is the PCI BDF on real systems.
    char linkbuf[256];
    ssize_t n = readlink(sysdev.c_str(), linkbuf, sizeof(linkbuf) - 1);
    if (n > 0) {
      linkbuf[n] = '\0';
      std::string link(linkbuf);
      auto slash = link.rfind('/');
      c.pci_bdf = slash == std::string::npos ? link : link.substr(slash + 1);
    }
    c.uuid = "tpu-" + std::string(h.gen->name) + "-w" +
             std::to_string(h.worker_id) + "-c" + std::to_string(idx);
    // Position in the sorted device list, not the raw accel index:
    // sparse indices (failed chip) must still map inside the grid.
    ChipCoords(h.slice, host, h.worker_id, static_cast<int>(pos), c.coords);
    h.chips.push_back(c);
  }
  return h;
}

void EmitHost(Json& j, const HostInfo& h) {
  j.raw("{");
  j.str("platform").raw(":").str(h.gen->name).raw(",");
  j.str("accelerator_type").raw(":").str(h.accelerator_type).raw(",");
  j.str("topology").raw(":").str(h.slice.str(h.gen->dims)).raw(",");
  j.str("num_slice_chips").raw(":").num(h.slice.count()).raw(",");
  j.str("num_hosts").raw(":").num(h.num_hosts).raw(",");
  j.str("worker_id").raw(":").num(h.worker_id).raw(",");
  j.str("chips_per_host").raw(":").num(h.gen->chips_per_host).raw(",");
  j.str("cores_per_chip").raw(":").num(h.gen->cores_per_chip).raw(",");
  j.str("hbm_bytes_per_chip").raw(":").num(h.gen->hbm_bytes).raw(",");
  j.str("chips").raw(":[");
  for (size_t i = 0; i < h.chips.size(); i++) {
    const Chip& c = h.chips[i];
    if (i) j.raw(",");
    j.raw("{");
    j.str("index").raw(":").num(c.index).raw(",");
    j.str("uuid").raw(":").str(c.uuid).raw(",");
    j.str("devpath").raw(":").str(c.devpath).raw(",");
    j.str("ici_coords").raw(":[").num(c.coords[0]).raw(",").num(c.coords[1])
        .raw(",").num(c.coords[2]).raw("],");
    j.str("numa_node").raw(":").num(c.numa_node).raw(",");
    j.str("pci_bdf").raw(":").str(c.pci_bdf).raw(",");
    j.str("healthy").raw(":").boolean(c.healthy);
    j.raw("}");
  }
  j.raw("],");
  j.str("source").raw(":").str(h.source);
  j.raw("}");
}

}  // namespace

extern "C" {

const char* tpuinfo_version(void) { return kVersion; }

char* tpuinfo_enumerate(const char* opts) {
  auto o = ParseOpts(opts);
  HostInfo h = o.count("mock_topology") ? MockEnumerate(o) : DevfsEnumerate(o);
  Json j;
  EmitHost(j, h);
  return j.release();
}

char* tpuinfo_subslice_profiles(const char* opts) {
  auto o = ParseOpts(opts);
  const Generation* gen = nullptr;
  int chips = 0;
  std::string type = Opt(o, "mock_topology");
  if (type.empty()) {
    const char* env = std::getenv("TPU_ACCELERATOR_TYPE");
    type = env != nullptr ? env : "v5e-4";
  }
  if (!ParseAcceleratorType(type, &gen, &chips)) {
    gen = FindGeneration("v5e");
    chips = 4;
  }
  Shape host = HostShape(*gen);
  int per_host = std::min(chips, gen->chips_per_host);
  // Host may own fewer chips than a full block (e.g. v5e-1).
  if (per_host < host.count()) {
    host = SliceShape(*gen, per_host);
  }

  Json j;
  j.raw("{").str("profiles").raw(":[");
  bool first = true;

  // Half-chip (single TensorCore) profile for megacore-capable chips:
  // the finest-grained carve-out, the analog of the smallest MIG profile.
  if (gen->cores_per_chip > 1) {
    j.raw("{");
    j.str("name").raw(":").str("1c").raw(",");
    j.str("chips").raw(":").num(0).raw(",");
    j.str("cores").raw(":").num(1).raw(",");
    j.str("hbm_bytes").raw(":").num(gen->hbm_bytes / gen->cores_per_chip)
        .raw(",");
    j.str("placements").raw(":[");
    for (int i = 0; i < per_host * gen->cores_per_chip; i++) {
      if (i) j.raw(",");
      j.num(i);
    }
    j.raw("]}");
    first = false;
  }

  // Aligned sub-block (power-of-two) chip carve-outs within the host
  // grid, over all three dims (z matters for 2-chip 3D hosts), the
  // analog of MIG profile x placement enumeration.
  for (int w = 1; w <= host.x; w *= 2) {
    for (int hgt = 1; hgt <= host.y; hgt *= 2) {
      for (int dep = 1; dep <= host.z; dep *= 2) {
        if (w * hgt * dep > per_host) continue;
        Shape prof{w, hgt, dep};
        if (!first) j.raw(",");
        first = false;
        j.raw("{");
        j.str("name").raw(":").str(prof.str(gen->dims)).raw(",");
        j.str("chips").raw(":").num(prof.count()).raw(",");
        j.str("cores").raw(":").num(prof.count() * gen->cores_per_chip)
            .raw(",");
        j.str("hbm_bytes").raw(":").num(prof.count() * gen->hbm_bytes)
            .raw(",");
        j.str("placements").raw(":[");
        bool p0 = true;
        for (int z = 0; z + dep <= host.z; z += dep) {
          for (int y = 0; y + hgt <= host.y; y += hgt) {
            for (int x = 0; x + w <= host.x; x += w) {
              if (!p0) j.raw(",");
              p0 = false;
              j.num((z * host.y + y) * host.x + x);
            }
          }
        }
        j.raw("]}");
      }
    }
  }
  j.raw("]}");
  return j.release();
}

namespace {

bool IsFatalKind(const std::string& kind) {
  return kind == "hbm_uncorrectable" || kind == "chip_lost" ||
         kind == "ici_link_down" || kind == "pcie_aer_fatal";
}

void EmitEvent(Json& j, bool& first, int chip, const std::string& kind) {
  if (!first) j.raw(",");
  first = false;
  j.raw("{").str("chip").raw(":").num(chip).raw(",")
      .str("kind").raw(":").str(kind).raw(",")
      .str("fatal").raw(":").boolean(IsFatalKind(kind)).raw("}");
}

// Sum of error counts in a sysfs AER attribute ("<errname> <count>" per
// line). A TOTAL_ERR_* line, when present, is authoritative (summing the
// per-kind lines too would double-count).
long long ReadAerCount(const std::string& path) {
  std::ifstream f(path);
  if (!f) return -1;  // attribute absent: source not available
  long long sum = 0;
  std::string name;
  long long count;
  while (f >> name >> count) {
    if (name.rfind("TOTAL", 0) == 0) return count;
    sum += count;
  }
  return sum;
}

}  // namespace

char* tpuinfo_health(const char* opts) {
  auto o = ParseOpts(opts);
  Json j;
  j.raw("{").str("events").raw(":[");
  bool first = true;
  std::string events = Opt(o, "health_events");
  if (!events.empty() && events[0] == '@') {
    // Control-file form (@/path): re-read per call so events can be
    // injected into a running plugin (mock-NVML control-file analog).
    std::ifstream f(events.substr(1));
    std::stringstream buf;
    if (f) buf << f.rdbuf();
    events = buf.str();
    // Full strip (both ends, all whitespace) -- must match the Python
    // backend's str.strip() exactly (backend-parity contract).
    size_t b = events.find_first_not_of(" \t\r\n\f\v");
    size_t e = events.find_last_not_of(" \t\r\n\f\v");
    events = (b == std::string::npos)
                 ? ""
                 : events.substr(b, e - b + 1);
  }
  if (!events.empty()) {
    std::stringstream ss(events);
    std::string item;
    while (std::getline(ss, item, '|')) {
      if (item.empty()) continue;
      int chip = -1;
      std::string kind = "unknown";
      std::stringstream fs(item);
      std::string field;
      while (std::getline(fs, field, ',')) {
        auto eq = field.find('=');
        if (eq == std::string::npos) continue;
        std::string k = field.substr(0, eq), v = field.substr(eq + 1);
        if (k == "chip") chip = std::atoi(v.c_str());
        if (k == "kind") kind = v;
      }
      EmitEvent(j, first, chip, kind);
    }
  }
  // Real-host sources (devfs mode only: the caller supplies the chip
  // baseline from its startup enumeration via expected_chips). TPU accel
  // devices expose no NVML-style event fd, so health is:
  //   1. enumeration diff -- a baseline chip whose /dev/accelN vanished
  //      is chip_lost (the GPU-lost analog, device_health.go:281-328);
  //   2. PCIe AER counters from the chip's sysfs device node --
  //      aer_dev_fatal / aer_dev_nonfatal (the XID analog).
  std::string expected = Opt(o, "expected_chips");
  if (!expected.empty() && o.count("mock_topology") == 0) {
    const std::string dev_root = Opt(o, "dev_root", "/dev");
    const std::string sys_root = Opt(o, "sys_root", "/sys");
    // PCI addresses aligned with expected_chips: the AER fallback for
    // hosts without an accel class node (vfio-bound, TPU-VM) -- the
    // counters are then read under /sys/bus/pci/devices/<bdf>/
    // (device_health.go:215-328: several event classes, one pipeline).
    std::vector<std::string> bdfs;
    {
      std::stringstream bs(Opt(o, "expected_bdfs"));
      std::string b;
      while (std::getline(bs, b, ',')) bdfs.push_back(b);
    }
    std::stringstream es(expected);
    std::string tok;
    size_t pos = 0;
    while (std::getline(es, tok, ',')) {
      if (tok.empty()) continue;
      const size_t my_pos = pos++;
      int idx = std::atoi(tok.c_str());
      std::string devpath = dev_root + "/accel" + std::to_string(idx);
      struct stat st;
      if (stat(devpath.c_str(), &st) != 0) {
        EmitEvent(j, first, idx, "chip_lost");
        continue;
      }
      std::string sysdev =
          sys_root + "/class/accel/accel" + std::to_string(idx) + "/device";
      std::string pcidev;
      if (my_pos < bdfs.size() && !bdfs[my_pos].empty())
        pcidev = sys_root + "/bus/pci/devices/" + bdfs[my_pos];
      struct AerAttr { const char* attr; const char* kind; };
      const AerAttr attrs[] = {
          {"aer_dev_fatal", "pcie_aer_fatal"},
          {"aer_dev_nonfatal", "pcie_aer_nonfatal"},
      };
      for (const auto& a : attrs) {
        long long count = ReadAerCount(sysdev + "/" + a.attr);
        if (count < 0 && !pcidev.empty())
          count = ReadAerCount(pcidev + "/" + a.attr);
        if (count > 0) EmitEvent(j, first, idx, a.kind);
      }
    }
  }
  j.raw("]}");
  return j.release();
}

void tpuinfo_free(char* p) { std::free(p); }

}  // extern "C"
