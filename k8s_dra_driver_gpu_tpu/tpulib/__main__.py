"""CLI: print this host's TPU enumeration as JSON.

Usage:
    TPULIB_MOCK_TOPOLOGY=v5p-16 python -m k8s_dra_driver_gpu_tpu.tpulib
"""

import dataclasses
import json

from .binding import EnumerateOptions, load


def main() -> None:
    lib = load()
    opts = EnumerateOptions.from_env()
    host = lib.enumerate(opts)
    doc = dataclasses.asdict(host)
    doc["backend"] = lib.name
    doc["profiles"] = [
        dataclasses.asdict(p) for p in lib.subslice_profiles(opts)
    ]
    print(json.dumps(doc, indent=2))


if __name__ == "__main__":
    main()
