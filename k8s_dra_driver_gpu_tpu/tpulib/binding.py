"""ctypes binding for libtpuinfo.so plus a pure-Python fallback backend.

Reference analog: the cgo layer in cmd/gpu-kubelet-plugin/nvlib.go that
dlopens libnvidia-ml.so.1 at a configurable driver root (root.go:28-63).
Here the native library is our own in-tree C++ (native/tpuinfo.cc); the
Python fallback mirrors its mock/devfs behavior so the rest of the stack
is backend-agnostic (and the mock path mirrors the reference's mock-NVML
strategy, hack/ci/mock-nvml/).
"""

from __future__ import annotations

import ctypes
import json
import os
import re
import subprocess
from dataclasses import dataclass, field

from ..pkg.faults import fault_point as _fault_point

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libtpuinfo.so")

# Env seams (mirrors mock-NVML env switches like ALT_PROC_DEVICES_PATH,
# internal/common/nvcaps.go:30-75).
ENV_MOCK_TOPOLOGY = "TPULIB_MOCK_TOPOLOGY"
ENV_MOCK_WORKER_ID = "TPULIB_MOCK_WORKER_ID"
ENV_MOCK_HEALTH_EVENTS = "TPULIB_MOCK_HEALTH_EVENTS"
# Per-tenant HBM/core usage injection for the telemetry seam
# (tenant_usage): "tenant=<key>,hbm=<bytes>[,cores=N]|..." or
# "@/path/to/control-file" re-read every poll, like health events.
ENV_MOCK_TENANT_USAGE = "TPULIB_MOCK_TENANT_USAGE"
# Per-chip power/thermal/utilization injection for the fleet-telemetry
# seam (chip_telemetry): "chip=0,power=120.5,temp=55,hbm=1073741824,
# duty=0.85,ici_err=3|chip=1,..." with the same "@control-file"
# re-read-every-poll form as health events.
ENV_MOCK_TELEMETRY = "TPULIB_MOCK_TELEMETRY"


class TpuLibError(RuntimeError):
    pass


@dataclass(frozen=True)
class TpuChip:
    index: int
    uuid: str
    devpath: str
    ici_coords: tuple[int, int, int]
    numa_node: int
    pci_bdf: str
    healthy: bool = True


@dataclass(frozen=True)
class TpuHostInfo:
    platform: str  # v4|v5e|v5p|v6e
    accelerator_type: str  # e.g. "v5p-16" ("" when undetectable)
    topology: str  # chip-grid dims of the full slice, e.g. "2x2x2"
    num_slice_chips: int
    num_hosts: int
    worker_id: int
    chips_per_host: int
    cores_per_chip: int
    hbm_bytes_per_chip: int
    chips: tuple[TpuChip, ...]
    source: str  # mock|devfs|none

    @property
    def topology_dims(self) -> tuple[int, ...]:
        return tuple(int(d) for d in self.topology.split("x"))


@dataclass(frozen=True)
class SubSliceProfile:
    """A valid carve-out of one host's chips (MIG-profile analog)."""

    name: str  # "1c" (single TensorCore) or chip-grid dims e.g. "2x1x1"
    chips: int  # 0 for core-level profiles
    cores: int
    hbm_bytes: int
    placements: tuple[int, ...]  # core index for "Nc", start chip otherwise

    @property
    def is_core_level(self) -> bool:
        return self.chips == 0


@dataclass(frozen=True)
class HealthEvent:
    chip: int
    kind: str
    fatal: bool


@dataclass(frozen=True)
class TenantUsage:
    """One per-tenant resource-usage sample (the live-telemetry seam
    the MISO sizing loop consumes, pkg/partition/profiles.py)."""

    tenant: str
    hbm_bytes: int
    cores: int = 1


@dataclass(frozen=True)
class ChipTelemetry:
    """One per-chip power/thermal/utilization sample (the node half of
    the fleet telemetry plane, kubeletplugin/health.py ->
    pkg/fleetstate.py). ``ici_link_errors`` is CUMULATIVE (a counter
    the consumer differentiates); everything else is instantaneous."""

    chip: int
    power_watts: float = 0.0
    temp_celsius: float = 0.0
    hbm_used_bytes: int = 0
    duty_cycle: float = 0.0  # 0.0-1.0
    ici_link_errors: int = 0

    def to_dict(self) -> dict:
        return {
            "chip": self.chip,
            "power_watts": self.power_watts,
            "temp_celsius": self.temp_celsius,
            "hbm_used_bytes": self.hbm_used_bytes,
            "duty_cycle": self.duty_cycle,
            "ici_link_errors": self.ici_link_errors,
        }


@dataclass(frozen=True)
class EnumerateOptions:
    mock_topology: str | None = None
    worker_id: int | None = None
    dev_root: str | None = None
    sys_root: str | None = None
    health_events: str | None = None
    # Comma-separated chip indices from the startup enumeration: the
    # baseline for devfs health (enumeration-diff chip_lost + AER poll).
    expected_chips: str | None = None
    # PCI addresses aligned with expected_chips: the AER fallback path
    # for hosts where the chip has no /sys/class/accel node (vfio-bound,
    # GKE TPU-VM) -- counters are then read under
    # /sys/bus/pci/devices/<bdf>/ instead.
    expected_bdfs: str | None = None

    @classmethod
    def from_env(cls) -> "EnumerateOptions":
        wid = os.environ.get(ENV_MOCK_WORKER_ID)
        return cls(
            mock_topology=os.environ.get(ENV_MOCK_TOPOLOGY),
            worker_id=_atoi(wid) if wid else None,
            health_events=os.environ.get(ENV_MOCK_HEALTH_EVENTS),
        )

    def encode(self) -> str:
        parts = []
        if self.mock_topology:
            parts.append(f"mock_topology={self.mock_topology}")
        if self.worker_id is not None:
            parts.append(f"worker_id={self.worker_id}")
        if self.dev_root:
            parts.append(f"dev_root={self.dev_root}")
        if self.sys_root:
            parts.append(f"sys_root={self.sys_root}")
        if self.health_events:
            parts.append(f"health_events={self.health_events}")
        if self.expected_chips:
            parts.append(f"expected_chips={self.expected_chips}")
        if self.expected_bdfs:
            parts.append(f"expected_bdfs={self.expected_bdfs}")
        return ";".join(parts)


def _host_from_json(doc: dict) -> TpuHostInfo:
    return TpuHostInfo(
        platform=doc["platform"],
        accelerator_type=doc["accelerator_type"],
        topology=doc["topology"],
        num_slice_chips=doc["num_slice_chips"],
        num_hosts=doc["num_hosts"],
        worker_id=doc["worker_id"],
        chips_per_host=doc["chips_per_host"],
        cores_per_chip=doc["cores_per_chip"],
        hbm_bytes_per_chip=doc["hbm_bytes_per_chip"],
        chips=tuple(
            TpuChip(
                index=c["index"],
                uuid=c["uuid"],
                devpath=c["devpath"],
                ici_coords=tuple(c["ici_coords"]),
                numa_node=c["numa_node"],
                pci_bdf=c["pci_bdf"],
                healthy=c["healthy"],
            )
            for c in doc["chips"]
        ),
        source=doc["source"],
    )


class NativeTpuLib:
    """Backend over the in-tree C++ library."""

    def __init__(self, so_path: str = _SO_PATH):
        if not os.path.exists(so_path):
            raise TpuLibError(f"{so_path} not built")
        self._lib = ctypes.CDLL(so_path)
        self._lib.tpuinfo_version.restype = ctypes.c_char_p
        for fn in ("tpuinfo_enumerate", "tpuinfo_subslice_profiles",
                   "tpuinfo_health"):
            getattr(self._lib, fn).restype = ctypes.c_void_p
            getattr(self._lib, fn).argtypes = [ctypes.c_char_p]
        self._lib.tpuinfo_free.argtypes = [ctypes.c_void_p]

    @property
    def name(self) -> str:
        return "native"

    def version(self) -> str:
        return self._lib.tpuinfo_version().decode()

    def _call(self, fn_name: str, opts: EnumerateOptions) -> dict:
        ptr = getattr(self._lib, fn_name)(opts.encode().encode())
        if not ptr:
            raise TpuLibError(f"{fn_name} returned NULL")
        try:
            return json.loads(ctypes.string_at(ptr).decode())
        finally:
            self._lib.tpuinfo_free(ptr)

    def enumerate(self, opts: EnumerateOptions | None = None) -> TpuHostInfo:
        _fault_point("tpulib.enumerate",
                     error=lambda m: TpuLibError(m))
        return _host_from_json(
            self._call("tpuinfo_enumerate", opts or EnumerateOptions.from_env())
        )

    def subslice_profiles(
        self, opts: EnumerateOptions | None = None
    ) -> tuple[SubSliceProfile, ...]:
        doc = self._call(
            "tpuinfo_subslice_profiles", opts or EnumerateOptions.from_env()
        )
        return tuple(
            SubSliceProfile(
                name=p["name"],
                chips=p["chips"],
                cores=p["cores"],
                hbm_bytes=p["hbm_bytes"],
                placements=tuple(p["placements"]),
            )
            for p in doc["profiles"]
        )

    def health(self, opts: EnumerateOptions | None = None) -> tuple[HealthEvent, ...]:
        _fault_point("tpulib.health", error=lambda m: TpuLibError(m))
        doc = self._call("tpuinfo_health", opts or EnumerateOptions.from_env())
        return tuple(
            HealthEvent(chip=e["chip"], kind=e["kind"], fatal=e["fatal"])
            for e in doc["events"]
        )

    def tenant_usage(
        self, opts: EnumerateOptions | None = None
    ) -> tuple[TenantUsage, ...]:
        """Per-tenant HBM/core usage samples. The native library
        exposes no per-tenant counters yet, so both backends share the
        Python-side source (the mock injection env / control file) --
        byte-identical parity by construction."""
        return _tenant_usage_from_env()

    def chip_telemetry(
        self, opts: EnumerateOptions | None = None
    ) -> tuple[ChipTelemetry, ...]:
        """Per-chip power/thermal/utilization samples. Like
        tenant_usage, the native library exposes no power rails yet,
        so both backends share the Python-side mock source --
        byte-identical parity by construction."""
        return _chip_telemetry_from_env()


def _chip_telemetry_from_env() -> tuple[ChipTelemetry, ...]:
    """Parse TPULIB_MOCK_TELEMETRY:
    ``chip=<i>[,power=<W>][,temp=<C>][,hbm=<bytes>][,duty=<0..1>]
    [,ici_err=<n>]|...`` with the same ``@control-file``
    re-read-every-poll form as health events. Empty / unset = no
    samples (a host without power rails degrades to no telemetry,
    never fake numbers)."""
    _fault_point("tpulib.telemetry", error=lambda m: TpuLibError(m))
    spec = os.environ.get(ENV_MOCK_TELEMETRY, "")
    if spec.startswith("@"):
        try:
            with open(spec[1:], encoding="latin-1") as f:
                spec = f.read().strip(" \t\r\n\f\v")
        except OSError:
            spec = ""
    samples = []
    for item in filter(None, spec.split("|")):
        chip = -1
        power = temp = duty = 0.0
        hbm = ici = 0
        for part in item.split(","):
            if "=" not in part:
                continue
            k, _, v = part.partition("=")
            if k == "chip":
                chip = _atoi(v)
            elif k == "power":
                power = _atof(v)
            elif k == "temp":
                temp = _atof(v)
            elif k == "hbm":
                hbm = _atoi(v)
            elif k == "duty":
                duty = _atof(v)
            elif k == "ici_err":
                ici = _atoi(v)
        if chip >= 0:
            samples.append(ChipTelemetry(
                chip=chip, power_watts=power, temp_celsius=temp,
                hbm_used_bytes=hbm, duty_cycle=duty,
                ici_link_errors=ici))
    return tuple(samples)


def _tenant_usage_from_env() -> tuple[TenantUsage, ...]:
    """Parse TPULIB_MOCK_TENANT_USAGE:
    ``tenant=<key>,hbm=<bytes>[,cores=N]|...`` with the same
    ``@control-file`` re-read-every-poll form as health events."""
    _fault_point("tpulib.tenant_usage", error=lambda m: TpuLibError(m))
    spec = os.environ.get(ENV_MOCK_TENANT_USAGE, "")
    if spec.startswith("@"):
        try:
            with open(spec[1:], encoding="latin-1") as f:
                spec = f.read().strip(" \t\r\n\f\v")
        except OSError:
            spec = ""
    samples = []
    for item in filter(None, spec.split("|")):
        tenant, hbm, cores = "", 0, 1
        for part in item.split(","):
            if "=" not in part:
                continue
            k, _, v = part.partition("=")
            if k == "tenant":
                tenant = v
            elif k == "hbm":
                hbm = _atoi(v)
            elif k == "cores":
                cores = max(1, _atoi(v))
        if tenant:
            samples.append(TenantUsage(tenant=tenant, hbm_bytes=hbm,
                                       cores=cores))
    return tuple(samples)


# ---------------------------------------------------------------------------
# Pure-Python backend (same contract; used when the .so is unavailable and
# as the parity oracle in tests)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class _Gen:
    name: str
    dims: int
    chips_per_host: int
    cores_per_chip: int
    hbm_bytes: int
    type_counts_cores: bool


_GENERATIONS = {
    g.name: g
    for g in [
        _Gen("v4", 3, 4, 2, 32 << 30, True),
        _Gen("v5e", 2, 4, 1, 16 << 30, False),
        _Gen("v5p", 3, 4, 2, 95 << 30, True),
        _Gen("v6e", 2, 4, 1, 32 << 30, False),
    ]
}

_SHAPES_3D = {1: (1, 1, 1), 2: (1, 1, 2), 4: (2, 2, 1), 8: (2, 2, 2),
              16: (2, 2, 4), 32: (2, 4, 4), 64: (4, 4, 4), 128: (4, 4, 8),
              256: (4, 8, 8), 512: (8, 8, 8)}
_SHAPES_2D = {1: (1, 1, 1), 2: (1, 2, 1), 4: (2, 2, 1), 8: (2, 4, 1),
              16: (4, 4, 1), 32: (4, 8, 1), 64: (8, 8, 1), 128: (8, 16, 1),
              256: (16, 16, 1)}

_FATAL_KINDS = {"hbm_uncorrectable", "chip_lost", "ici_link_down",
                "pcie_aer_fatal"}


def _read_aer_count(path: str) -> int:
    """Sum of counts in a sysfs AER attribute ("<errname> <count>" per
    line); a TOTAL_ERR_* line is authoritative. -1 = attribute absent."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return -1
    # Token-pair stream parse, matching the native backend's
    # `f >> name >> count` loop (stops at the first non-numeric count).
    tokens = text.split()
    total = 0
    for i in range(0, len(tokens) - 1, 2):
        try:
            count = int(tokens[i + 1])
        except ValueError:
            break
        if tokens[i].startswith("TOTAL"):
            return count
        total += count
    return total


def _atoi(s: str) -> int:
    """C atoi semantics (the native backend parses with atoi): leading
    integer prefix, 0 when there is none."""
    m = re.match(r"\s*[+-]?\d+", s)
    return int(m.group()) if m else 0


def _atof(s: str) -> float:
    """C atof semantics to match _atoi: leading float prefix, 0.0 when
    there is none (telemetry grammar values are never exponents)."""
    m = re.match(r"\s*[+-]?\d*\.?\d+", s)
    return float(m.group()) if m else 0.0


def _parse_type(t: str) -> tuple[_Gen, int] | None:
    m = re.fullmatch(r"(v\d+\w*)-(\d+)", t)
    if not m or m.group(1) not in _GENERATIONS:
        return None
    g = _GENERATIONS[m.group(1)]
    n = int(m.group(2))
    chips = n // g.cores_per_chip if g.type_counts_cores else n
    return (g, chips) if chips > 0 else None


def _slice_shape(g: _Gen, chips: int) -> tuple[int, int, int]:
    tbl = _SHAPES_3D if g.dims == 3 else _SHAPES_2D
    return tbl.get(chips, (1, chips, 1))


def _host_shape(g: _Gen) -> tuple[int, int, int]:
    return {8: (2, 4, 1), 4: (2, 2, 1), 2: (1, 2, 1)}.get(
        g.chips_per_host, (1, 1, 1)
    )


def _chip_coords(slice_s, host_s, worker: int, local: int) -> tuple[int, int, int]:
    bx = max(slice_s[0] // host_s[0], 1)
    by = max(slice_s[1] // host_s[1], 1)
    wx, wy, wz = worker % bx, (worker // bx) % by, worker // (bx * by)
    lx = local % host_s[0]
    ly = (local // host_s[0]) % host_s[1]
    lz = local // (host_s[0] * host_s[1])
    return (wx * host_s[0] + lx, wy * host_s[1] + ly, wz * host_s[2] + lz)


def _shape_str(s: tuple[int, int, int], dims: int) -> str:
    return f"{s[0]}x{s[1]}" if dims == 2 else f"{s[0]}x{s[1]}x{s[2]}"


class PyTpuLib:
    """Pure-Python backend implementing the tpuinfo contract."""

    @property
    def name(self) -> str:
        return "python"

    def version(self) -> str:
        return "0.1.0"

    def enumerate(self, opts: EnumerateOptions | None = None) -> TpuHostInfo:
        _fault_point("tpulib.enumerate",
                     error=lambda m: TpuLibError(m))
        opts = opts or EnumerateOptions.from_env()
        if opts.mock_topology:
            return self._mock(opts)
        return self._devfs(opts)

    def _mock(self, opts: EnumerateOptions) -> TpuHostInfo:
        parsed = _parse_type(opts.mock_topology or "")
        if parsed is None:
            g, chips, acc = _GENERATIONS["v5e"], 4, "v5e-4"
        else:
            (g, chips), acc = parsed, opts.mock_topology
        slice_s = _slice_shape(g, chips)
        host_s = _host_shape(g)
        per_host = min(chips, g.chips_per_host)
        if per_host < host_s[0] * host_s[1] * host_s[2]:
            # A partial host covers the (smaller) slice grid itself.
            host_s = _slice_shape(g, per_host)
        num_hosts = -(-chips // g.chips_per_host)
        worker = opts.worker_id or 0
        chip_list = []
        for i in range(per_host):
            chip_list.append(
                TpuChip(
                    index=i,
                    uuid=f"tpu-{acc}-w{worker}-c{i}",
                    devpath=f"/dev/accel{i}",
                    ici_coords=_chip_coords(slice_s, host_s, worker, i),
                    numa_node=0 if i < per_host // 2 else (1 if per_host > 1 else 0),
                    pci_bdf=f"0000:00:{4 + i:02x}.0",
                )
            )
        return TpuHostInfo(
            platform=g.name,
            accelerator_type=acc,
            topology=_shape_str(slice_s, g.dims),
            num_slice_chips=slice_s[0] * slice_s[1] * slice_s[2],
            num_hosts=num_hosts,
            worker_id=worker,
            chips_per_host=g.chips_per_host,
            cores_per_chip=g.cores_per_chip,
            hbm_bytes_per_chip=g.hbm_bytes,
            chips=tuple(chip_list),
            source="mock",
        )

    def _devfs(self, opts: EnumerateOptions) -> TpuHostInfo:
        dev_root = opts.dev_root or "/dev"
        sys_root = opts.sys_root or "/sys"
        type_env = os.environ.get("TPU_ACCELERATOR_TYPE", "")
        parsed = _parse_type(type_env)
        if parsed is None:
            g, slice_chips, acc = _GENERATIONS["v5e"], 0, ""
        else:
            (g, slice_chips), acc = parsed, type_env
        indices = sorted(
            int(m.group(1))
            for name in (os.listdir(dev_root) if os.path.isdir(dev_root) else [])
            if (m := re.fullmatch(r"accel(\d+)", name))
        )
        source = "devfs" if indices else "none"
        if slice_chips == 0:
            slice_chips = len(indices) or 1
        slice_s = _slice_shape(g, slice_chips)
        host_s = _host_shape(g)
        if len(indices) < host_s[0] * host_s[1] * host_s[2] and indices:
            host_s = _slice_shape(g, len(indices))
        worker = _atoi(os.environ.get("TPU_WORKER_ID", "0") or "0")
        chip_list = []
        for pos, idx in enumerate(indices):
            sysdev = f"{sys_root}/class/accel/accel{idx}/device"
            numa_node = -1
            try:
                with open(f"{sysdev}/numa_node") as f:
                    numa_node = int(f.read().strip() or -1)
            except (OSError, ValueError):
                pass
            pci_bdf = ""
            try:
                pci_bdf = os.path.basename(os.readlink(sysdev))
            except OSError:
                pass
            chip_list.append(
                TpuChip(
                    index=idx,
                    uuid=f"tpu-{g.name}-w{worker}-c{idx}",
                    devpath=f"{dev_root}/accel{idx}",
                    # Position in the sorted device list, not the raw accel
                    # index: sparse indices (failed chip) must still map
                    # inside the (possibly reduced) host grid.
                    ici_coords=_chip_coords(slice_s, host_s, worker, pos),
                    numa_node=numa_node,
                    pci_bdf=pci_bdf,
                )
            )
        return TpuHostInfo(
            platform=g.name,
            accelerator_type=acc,
            topology=_shape_str(slice_s, g.dims),
            num_slice_chips=slice_s[0] * slice_s[1] * slice_s[2],
            num_hosts=-(-slice_chips // g.chips_per_host),
            worker_id=worker,
            chips_per_host=g.chips_per_host,
            cores_per_chip=g.cores_per_chip,
            hbm_bytes_per_chip=g.hbm_bytes,
            chips=tuple(chip_list),
            source=source,
        )

    def subslice_profiles(
        self, opts: EnumerateOptions | None = None
    ) -> tuple[SubSliceProfile, ...]:
        opts = opts or EnumerateOptions.from_env()
        t = opts.mock_topology or os.environ.get("TPU_ACCELERATOR_TYPE", "v5e-4")
        parsed = _parse_type(t)
        g, chips = parsed if parsed else (_GENERATIONS["v5e"], 4)
        host_s = _host_shape(g)
        per_host = min(chips, g.chips_per_host)
        if per_host < host_s[0] * host_s[1] * host_s[2]:
            host_s = _slice_shape(g, per_host)
        profiles = []
        if g.cores_per_chip > 1:
            profiles.append(
                SubSliceProfile(
                    name="1c",
                    chips=0,
                    cores=1,
                    hbm_bytes=g.hbm_bytes // g.cores_per_chip,
                    placements=tuple(range(per_host * g.cores_per_chip)),
                )
            )
        w = 1
        while w <= host_s[0]:
            h = 1
            while h <= host_s[1]:
                d = 1
                while d <= host_s[2]:
                    if w * h * d <= per_host:
                        placements = tuple(
                            (z * host_s[1] + y) * host_s[0] + x
                            for z in range(0, host_s[2] - d + 1, d)
                            for y in range(0, host_s[1] - h + 1, h)
                            for x in range(0, host_s[0] - w + 1, w)
                        )
                        profiles.append(
                            SubSliceProfile(
                                name=_shape_str((w, h, d), g.dims),
                                chips=w * h * d,
                                cores=w * h * d * g.cores_per_chip,
                                hbm_bytes=w * h * d * g.hbm_bytes,
                                placements=placements,
                            )
                        )
                    d *= 2
                h *= 2
            w *= 2
        return tuple(profiles)

    def health(self, opts: EnumerateOptions | None = None) -> tuple[HealthEvent, ...]:
        _fault_point("tpulib.health", error=lambda m: TpuLibError(m))
        opts = opts or EnumerateOptions.from_env()
        events = []
        spec = opts.health_events or ""
        if spec.startswith("@"):
            # Control-file form: re-read every poll so a running plugin
            # can have health events injected/cleared at runtime (the
            # mock-NVML control-file analog). latin-1 + explicit ASCII
            # strip = byte-for-byte what the native backend does, so
            # arbitrary file bytes cannot diverge the two.
            try:
                with open(spec[1:], encoding="latin-1") as f:
                    spec = f.read().strip(" \t\r\n\f\v")
            except OSError:
                spec = ""
        for item in filter(None, spec.split("|")):
            chip, kind = -1, "unknown"
            for f in item.split(","):
                if "=" not in f:
                    continue
                k, _, v = f.partition("=")
                if k == "chip":
                    chip = _atoi(v)
                elif k == "kind":
                    kind = v
            events.append(
                HealthEvent(chip=chip, kind=kind, fatal=kind in _FATAL_KINDS)
            )
        # Real-host sources (devfs mode only; see tpuinfo.cc): baseline
        # enumeration-diff -> chip_lost, plus PCIe AER counters.
        if opts.expected_chips and not opts.mock_topology:
            dev_root = opts.dev_root or "/dev"
            sys_root = opts.sys_root or "/sys"
            bdfs = (opts.expected_bdfs or "").split(",")
            for pos, tok in enumerate(
                    filter(None, opts.expected_chips.split(","))):
                idx = _atoi(tok)
                if not os.path.exists(f"{dev_root}/accel{idx}"):
                    events.append(
                        HealthEvent(chip=idx, kind="chip_lost", fatal=True))
                    continue
                sysdev = f"{sys_root}/class/accel/accel{idx}/device"
                # Fallback by PCI address: vfio-bound or TPU-VM hosts may
                # expose no accel class node (device_health.go:215-328
                # keeps multiple event classes in one pipeline).
                bdf = bdfs[pos].strip() if pos < len(bdfs) else ""
                pcidev = f"{sys_root}/bus/pci/devices/{bdf}" if bdf else ""
                for attr, kind, fatal in (
                    ("aer_dev_fatal", "pcie_aer_fatal", True),
                    ("aer_dev_nonfatal", "pcie_aer_nonfatal", False),
                ):
                    count = _read_aer_count(f"{sysdev}/{attr}")
                    if count < 0 and pcidev:
                        count = _read_aer_count(f"{pcidev}/{attr}")
                    if count > 0:
                        events.append(
                            HealthEvent(chip=idx, kind=kind, fatal=fatal))
        return tuple(events)

    def tenant_usage(
        self, opts: EnumerateOptions | None = None
    ) -> tuple[TenantUsage, ...]:
        """Per-tenant HBM/core usage samples (mock injection env /
        control file; same source as the native backend)."""
        return _tenant_usage_from_env()

    def chip_telemetry(
        self, opts: EnumerateOptions | None = None
    ) -> tuple[ChipTelemetry, ...]:
        """Per-chip power/thermal/utilization samples (mock injection
        env / control file; same source as the native backend)."""
        return _chip_telemetry_from_env()


def load(prefer_native: bool = True, build_if_missing: bool = True):
    """Load the device library: native if built (building it on demand
    when a toolchain is present), else the Python backend.

    Mirrors the reference's runtime driver-root library location
    (root.go:28-63): the library is found relative to this package.
    """
    if prefer_native:
        if not os.path.exists(_SO_PATH) and build_if_missing:
            try:
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except (OSError, subprocess.SubprocessError):
                pass
        try:
            return NativeTpuLib()
        except (TpuLibError, OSError):
            pass
    return PyTpuLib()
