"""tpulib: the TPU device layer (NVML-analog).

Reference: cmd/gpu-kubelet-plugin/nvlib.go (deviceLib over cgo/NVML).
Here the native core is in-tree C++ (native/tpuinfo.cc) exposed through a
C API and loaded via ctypes; a pure-Python backend implements the same
contract for environments without the built library, and a parity test
keeps the two honest.
"""

from .binding import (
    HealthEvent,
    NativeTpuLib,
    PyTpuLib,
    SubSliceProfile,
    TpuChip,
    TpuHostInfo,
    TpuLibError,
    load,
)

__all__ = [
    "HealthEvent",
    "NativeTpuLib",
    "PyTpuLib",
    "SubSliceProfile",
    "TpuChip",
    "TpuHostInfo",
    "TpuLibError",
    "load",
]
