"""CDI spec generation: inject TPU devices + libtpu + env into containers.

Reference: cmd/gpu-kubelet-plugin/cdi.go -- per-claim transient CDI specs
(vendor k8s.gpu.nvidia.com, class claim, :44-49), cached common edits and
per-UUID device specs (:112-147), merged sharing edits per group
(:181-307). The GPU build injects /dev/nvidia*, driver libs and
NVIDIA_VISIBLE_DEVICES; the TPU build injects /dev/accel* (or /dev/vfio),
a libtpu.so mount, and the TPU_*/JAX env contract a JAX workload needs to
address exactly the claimed chips:

  TPU_VISIBLE_DEVICES        comma-separated local chip indices
                             (claim-scoped; last-wins when a pod holds
                             several claims -- see TPU_DEVICE_<i>)
  TPU_DEVICE_<i>=1           one marker per claimed chip, set on the
                             chip's own CDI device entry; unique names
                             merge as the UNION across claims, so the
                             full visible set is always recoverable
  TPU_ACCELERATOR_TYPE       e.g. v5p-16 (claim-scoped sub-topology)
  TPU_TOPOLOGY               chip-grid dims of the claimed devices
  TPU_WORKER_ID              this host's worker index in the slice
  TPU_WORKER_HOSTNAMES       filled by the ComputeDomain stack (multi-host)
  TPU_SKIP_MDS_QUERY=1       no GCE metadata dependency in-cluster
  TPU_CHIPS_PER_HOST_BOUNDS / TPU_PROCESS_BOUNDS for sub-host carve-outs
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field

from ..pkg.fsutil import stat_signature
from . import CDI_CLASS, CDI_VENDOR

CDI_VERSION = "0.6.0"
DEFAULT_CDI_ROOT = "/var/run/cdi"
DEFAULT_LIBTPU_PATH = "/usr/lib/libtpu.so"


@dataclass
class ContainerEdits:
    env: list[str] = field(default_factory=list)
    device_nodes: list[str] = field(default_factory=list)
    # (hostPath, containerPath, read_only). Library mounts are ro; shared
    # rendezvous dirs (tenancy) must stay writable.
    mounts: list[tuple[str, str, bool]] = field(default_factory=list)
    # OCI hooks the runtime executes on the host (nvidia-cdi-hook analog,
    # gpu main.go:293): (hookName, path, args). The tenancy preflight
    # rides a createContainer hook so a DENIED admission fails the start.
    hooks: list[tuple[str, str, list[str]]] = field(default_factory=list)

    def to_dict(self) -> dict:
        out: dict = {}
        if self.env:
            out["env"] = self.env
        if self.device_nodes:
            out["deviceNodes"] = [{"path": p} for p in self.device_nodes]
        if self.mounts:
            out["mounts"] = [
                {
                    "hostPath": h,
                    "containerPath": c,
                    "options": (["ro"] if ro else ["rw"])
                    + ["nosuid", "nodev", "bind"],
                }
                for h, c, ro in self.mounts
            ]
        if self.hooks:
            # timeout: the runtime kills a hung hook (wedged agent) so a
            # pod never sits in ContainerCreating forever; for
            # createContainer that reads as fail-closed.
            out["hooks"] = [
                {"hookName": name, "path": path, "args": args,
                 "timeout": 10}
                for name, path, args in self.hooks
            ]
        return out

    def merge(self, other: "ContainerEdits") -> "ContainerEdits":
        return ContainerEdits(
            env=self.env + other.env,
            device_nodes=self.device_nodes + other.device_nodes,
            mounts=self.mounts + other.mounts,
            hooks=self.hooks + other.hooks,
        )


def qualified_device_id(name: str) -> str:
    return f"{CDI_VENDOR}/{CDI_CLASS}={name}"


class CDIHandler:
    """Writes per-claim transient CDI spec files under the CDI root."""

    def __init__(
        self,
        cdi_root: str = DEFAULT_CDI_ROOT,
        libtpu_path: str = DEFAULT_LIBTPU_PATH,
    ):
        self._root = cdi_root
        self._libtpu = libtpu_path
        # Stat-validated parse cache: claim_uid -> ((mtime_ns, size,
        # ino), parsed spec). A warm repeat-prepare's idempotent check
        # pays a stat instead of a read+json.loads; an externally
        # rewritten (or crash-truncated) file misses the cache.
        self._spec_cache: dict[str, tuple[tuple[int, int, int], dict]] = {}
        self._spec_cache_lock = threading.Lock()
        os.makedirs(self._root, exist_ok=True)

    def _spec_path(self, claim_uid: str) -> str:
        return os.path.join(
            self._root, f"{CDI_VENDOR}-{CDI_CLASS}_{claim_uid}.json"
        )

    def common_edits(self, host) -> ContainerEdits:
        """Edits shared by every claim on this host (GetCommonEditsCached
        analog, cdi.go:112): libtpu mount + host-level env.

        The two TPU_DRA_MIGRATION_* vars are the cooperative-migration
        env contract (pkg/migration): they name the claim annotations a
        migration-capable workload watches for the checkpoint signal
        and writes its ack to, so the container needs no hardcoded
        knowledge of the driver's annotation namespace."""
        edits = ContainerEdits(
            env=[
                "TPU_SKIP_MDS_QUERY=1",
                f"TPU_ACCELERATOR_TYPE={host.accelerator_type}",
                f"TPU_WORKER_ID={host.worker_id}",
                ("TPU_DRA_MIGRATION_INTENT_ANNOTATION="
                 "resource.tpu.dra/migration-intent"),
                ("TPU_DRA_MIGRATION_ACK_ANNOTATION="
                 "resource.tpu.dra/migration-ack"),
            ],
        )
        if os.path.exists(self._libtpu):
            edits.mounts.append((self._libtpu, DEFAULT_LIBTPU_PATH, True))
        return edits

    def create_claim_spec_file(
        self,
        claim_uid: str,
        device_edits: dict[str, ContainerEdits],
        common: ContainerEdits | None = None,
    ) -> list[str]:
        """Write the transient spec for a claim; returns the qualified CDI
        device IDs (CreateClaimSpecFile analog, cdi.go:181)."""
        devices = [
            {"name": name, "containerEdits": edits.to_dict()}
            for name, edits in sorted(device_edits.items())
        ]
        spec = {
            "cdiVersion": CDI_VERSION,
            "kind": f"{CDI_VENDOR}/{CDI_CLASS}",
            "devices": devices,
        }
        if common and common.to_dict():
            spec["containerEdits"] = common.to_dict()
        tmp = self._spec_path(claim_uid) + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(spec, f, indent=1)
            f.flush()
            # No fsync: CDI specs are transient and regenerated by a
            # retried Prepare after any crash (the checkpoint, which IS
            # fsync'd, is the recovery anchor). Saves ~1ms per prepare.
        os.replace(tmp, self._spec_path(claim_uid))
        sig = self._stat_sig(claim_uid)
        if sig is not None:
            with self._spec_cache_lock:
                self._spec_cache[claim_uid] = (sig, spec)
        return [qualified_device_id(d["name"]) for d in devices]

    def list_claim_uids(self) -> list[str]:
        """Claim uids with a transient spec file on disk -- the
        reconcile sweep's CDI-layer inventory (orphan = a uid here with
        no checkpoint record)."""
        prefix = f"{CDI_VENDOR}-{CDI_CLASS}_"
        try:
            names = os.listdir(self._root)
        except FileNotFoundError:
            return []
        return [
            name[len(prefix):-len(".json")]
            for name in names
            if name.startswith(prefix) and name.endswith(".json")
        ]

    def delete_claim_spec_file(self, claim_uid: str) -> None:
        with self._spec_cache_lock:
            self._spec_cache.pop(claim_uid, None)
        try:
            os.unlink(self._spec_path(claim_uid))
        except FileNotFoundError:
            pass

    def spec_exists(self, claim_uid: str) -> bool:
        return os.path.exists(self._spec_path(claim_uid))

    def _stat_sig(self, claim_uid: str) -> tuple[int, int, int] | None:
        return stat_signature(self._spec_path(claim_uid))

    def read_spec(self, claim_uid: str) -> dict | None:
        """None when absent; raises ValueError on corrupt JSON (a
        crash-truncated un-fsync'd spec)."""
        sig = self._stat_sig(claim_uid)
        if sig is None:
            with self._spec_cache_lock:
                self._spec_cache.pop(claim_uid, None)
            return None
        with self._spec_cache_lock:
            cached = self._spec_cache.get(claim_uid)
        if cached is not None and cached[0] == sig:
            return cached[1]
        try:
            with open(self._spec_path(claim_uid), encoding="utf-8") as f:
                spec = json.load(f)
        except FileNotFoundError:
            return None
        except json.JSONDecodeError as e:
            with self._spec_cache_lock:
                self._spec_cache.pop(claim_uid, None)
            raise ValueError(f"corrupt CDI spec for {claim_uid}: {e}") from e
        with self._spec_cache_lock:
            self._spec_cache[claim_uid] = (sig, spec)
        return spec
