"""ResourceClaim model: the slice of resource.k8s.io the plugin consumes.

A claim arrives from the kubelet as the full ResourceClaim object; the
plugin needs its UID/namespace/name, the allocation results targeting
this driver, and the opaque device configs (class- and claim-sourced)
with their request scoping (reference device_state.go:689-776,
GetOpaqueDeviceConfigs :1138).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from . import DRIVER_NAME


@dataclass(frozen=True)
class DeviceResult:
    """One allocated device (status.allocation.devices.results[i])."""

    request: str
    driver: str
    pool: str
    device: str  # canonical device name from the ResourceSlice


@dataclass(frozen=True)
class OpaqueConfig:
    """One opaque config entry with its request scoping and source."""

    parameters: dict
    requests: tuple[str, ...]  # empty = applies to all requests
    source: str  # "FromClass" | "FromClaim"

    def applies_to(self, request: str) -> bool:
        return not self.requests or request in self.requests


@dataclass
class ResourceClaim:
    uid: str
    namespace: str = "default"
    name: str = ""
    results: list[DeviceResult] = field(default_factory=list)
    configs: list[OpaqueConfig] = field(default_factory=list)
    # Object annotations: the cross-binary trace context rides here
    # (resource.tpu.dra/traceparent, stamped by the scheduler's
    # allocation patch -- pkg/tracing.py).
    annotations: dict = field(default_factory=dict)

    @classmethod
    def from_dict(cls, obj: dict, driver: str = DRIVER_NAME) -> "ResourceClaim":
        meta = obj.get("metadata", {})
        alloc = (obj.get("status") or {}).get("allocation") or {}
        devices = alloc.get("devices") or {}
        results = [
            DeviceResult(
                request=r.get("request", ""),
                driver=r.get("driver", ""),
                pool=r.get("pool", ""),
                device=r.get("device", ""),
            )
            for r in devices.get("results", [])
            if r.get("driver", driver) == driver
        ]
        configs = []
        for c in devices.get("config", []):
            opaque = c.get("opaque") or {}
            if opaque.get("driver", driver) != driver:
                continue
            configs.append(
                OpaqueConfig(
                    parameters=opaque.get("parameters", {}),
                    requests=tuple(c.get("requests", [])),
                    source=c.get("source", "FromClaim"),
                )
            )
        return cls(
            uid=meta.get("uid", ""),
            namespace=meta.get("namespace", "default"),
            name=meta.get("name", ""),
            results=results,
            configs=configs,
            annotations=dict(meta.get("annotations") or {}),
        )
