"""Driver: DRA callbacks + ResourceSlice publication + health wiring.

Reference: cmd/gpu-kubelet-plugin/driver.go -- NewDriver (:70),
PrepareResourceClaims loop (:337), nodePrepareResource (:373) under the
node-global flock, ResourceSlice publication in legacy/combined/split
modes with server-version sniffing (:190, :574), health events ->
DeviceTaints -> republish (:496-566).
"""

from __future__ import annotations

import logging
import os
import time
from concurrent.futures import ThreadPoolExecutor

from . import DRIVER_NAME
from ..pkg import fleetstate, flightrecorder, tracing
from ..pkg.events import emit_warning_event
from ..pkg.kubeclient import NotFoundError
from ..pkg.metrics import DRARequestMetrics
from ..pkg.partition.profiles import TenantProfileStore
from ..pkg.schedcache import ATTR_POWER_CAP, power_cap_env
from ..pkg.sliceutil import publish_resource_slices, slice_content_hash
from .claim import ResourceClaim
from .cleanup import CheckpointCleanupManager
from .device_state import Config, DeviceState
from .deviceinfo import DeviceKind
from .health import ChipHealthMonitor, DeviceTaint
from .partitions import consumed_counters, shared_counter_sets
from .reconcile import NodeStateReconciler
from .subslice import chip_name

logger = logging.getLogger(__name__)

RESOURCE_GROUP = "resource.k8s.io"
RESOURCE_VERSION = "v1"

# Telemetry slice-attribute quantization: raw power/thermal wiggles
# every poll, so publishing raw values would turn the zero-write
# converged republish into a per-poll slice rewrite. Quantized to
# these steps, steady-state telemetry hashes identically and the
# content-hash diff short-circuits to zero kube calls; a real shift
# (a chip heating 5C, a node picking up 10W) still lands within one
# poll. TPU_DRA_TELEMETRY_ATTRS=0 disables attribute publication
# entirely (the ring/metrics/anomaly stations keep running).
TELEMETRY_POWER_STEP_W = 10
TELEMETRY_TEMP_STEP_C = 5
TELEMETRY_DUTY_STEP_PCT = 10
TELEMETRY_HBM_STEP_PCT = 10
# The cumulative ICI error counter is quantized too: a chronic 1-per-
# poll trickle (below the anomaly burst threshold) must not turn into
# one slice write per poll. Error-rate detail lives in the metrics
# counter + anomaly taints; the attribute is the coarse fleet signal.
TELEMETRY_ICI_STEP = 100


class Driver:
    """The per-node driver. Talks to the API server through any object
    with the KubeClient surface (FakeKubeClient in tests)."""

    def __init__(
        self,
        config: Config,
        kube_client,
        node_name: str,
        metrics: DRARequestMetrics | None = None,
        enable_health_monitor: bool = True,
        publication_mode: str | None = None,
        additional_ignored_health_kinds: tuple[str, ...] = (),
        resilience=None,  # pkg.metrics.ResilienceMetrics | None
        recovery_metrics=None,  # pkg.metrics.RecoveryMetrics | None
    ):
        self.state = DeviceState(config)
        self.kube = kube_client
        self.node_name = node_name
        self.metrics = metrics or DRARequestMetrics()
        if self.state.partition_engine is not None:
            from ..pkg.metrics import PartitionMetrics  # noqa: PLC0415

            self.state.partition_engine.metrics = PartitionMetrics(
                registry=self.metrics.registry)
            self.state.partition_engine.metrics.set_active(
                self.state.partition_engine.active_partitions())
        # Export the SegmentTimer breakdown (prep_lock_wait,
        # ckpt_fsync_wait, ...) through the request-metrics registry.
        self.state.segment_observer = self.metrics.observe_segments
        self._taints: dict[str, list[dict]] = {}
        # Quantized per-device telemetry attributes merged into the
        # published slices (the scheduler's FleetAggregator folds
        # them); see the TELEMETRY_*_STEP constants above.
        self._telemetry_attrs: dict[str, dict] = {}
        self._telemetry_attrs_enabled = (
            fleetstate.telemetry_enabled()
            and os.environ.get("TPU_DRA_TELEMETRY_ATTRS", "1")
            not in ("0", "false", "False"))
        # This node's power cap in watts (TPU_DRA_POWER_CAP_W, 0 =
        # uncapped): published as a powerCapWatts attribute on every
        # chip device so the scheduler's power-budget counter model
        # (pkg/schedcache) and the fleet headroom gauge see it.
        self._power_cap_w = power_cap_env()
        # Publication modes mirror the reference's three
        # (driver.go:190,574): "legacy" (pre-partitionable-devices
        # servers: one slice, whole chips only), "combined" (one slice,
        # chips + partitions + shared counters), "split" (KEP-4815
        # two-slice layout, needs a server >= 1.35 -- sniffed when not
        # forced).
        if publication_mode is None:
            publication_mode = (
                "split" if self._server_supports_split() else "combined"
            )
        if publication_mode not in ("legacy", "combined", "split"):
            raise ValueError(f"unknown publication mode {publication_mode!r}")
        self.publication_mode = publication_mode

        # Content hashes of the last slice set this driver successfully
        # published: the health-event republish path short-circuits to
        # ZERO kube calls when a poll reconciles to an unchanged taint
        # set (the publish-level diff additionally protects explicit
        # publishes, at the cost of one list read). The memo is
        # re-verified against LIVE state every TPU_DRA_PUBLISH_RECHECK_S
        # (a list read, zero writes when converged), so a slice deleted
        # or mutated behind our back still self-heals within one recheck
        # window instead of never.
        self._published_hashes: tuple | None = None
        self._published_verified_at = 0.0
        try:
            self._publish_recheck_s = float(os.environ.get(
                "TPU_DRA_PUBLISH_RECHECK_S", "300"))
        except ValueError:
            self._publish_recheck_s = 300.0

        self.cleanup = CheckpointCleanupManager(self.state, kube_client)
        # Cross-layer reconcile sweep (kubeletplugin/reconcile.py):
        # wraps the stale-claim GC and additionally repairs orphans in
        # every node-local layer (CDI specs, carve-outs, leases) and
        # re-declares failure for claims whose devices vanished.
        self.reconciler = NodeStateReconciler(
            self.state, kube_client, cleanup=self.cleanup,
            metrics=recovery_metrics, node_name=node_name)
        # Live tenant-demand store (MISO sizing input, pkg/partition/
        # profiles.py): fed by the health-poll loop's tpulib telemetry
        # samples below, so partition re-plans size against OBSERVED
        # per-tenant HBM/core usage instead of static files only.
        self.tenant_profiles = TenantProfileStore()
        # Serving-autoscaler seam (pkg/autoscale): when the partition
        # engine is enabled, a PartitionSet CRD watcher makes the
        # cluster-scoped layout the source of truth -- every matching
        # CRD update converges through apply_partition_set, the
        # startup layout (file or empty) survives as the bootstrap
        # fallback, and a malformed CRD keeps the last good plan
        # active. TPU_DRA_PARTITION_WATCH=0 restores the
        # startup-only-file behavior.
        self.partition_watcher = None
        if self.state.partition_engine is not None and os.environ.get(
                "TPU_DRA_PARTITION_WATCH", "1") not in ("0", "false",
                                                        "False"):
            from ..pkg.autoscale import (  # noqa: PLC0415
                PartitionSetWatcher,
            )

            self.partition_watcher = PartitionSetWatcher(
                kube_client,
                pool=config.pool_name or node_name,
                apply_fn=self.apply_partition_set,
                bootstrap=self.state.partition_engine.partition_set,
                prewarm_fn=self.apply_prewarm)
        self.health_monitor = None
        if enable_health_monitor:
            # The startup enumeration is the health baseline: a chip seen
            # here whose devfs entry later vanishes is chip_lost, and its
            # sysfs AER counters are polled (device_health.go:215-328
            # analog). Mock mode ignores expected_chips and uses injected
            # events only.
            import dataclasses  # noqa: PLC0415

            baseline = sorted(
                (d.chip.chip.index, d.chip.chip.pci_bdf or "")
                for d in self.state.allocatable.values()
                if d.kind == DeviceKind.CHIP
            )
            monitor_opts = dataclasses.replace(
                config.tpulib_opts,
                expected_chips=",".join(str(i) for i, _ in baseline),
                # AER fallback path for class-less hosts (see binding.py)
                expected_bdfs=",".join(b for _, b in baseline),
            )
            on_quarantine = None
            if resilience is not None:
                on_quarantine = (
                    lambda device: resilience.quarantines.labels(
                        device).inc())
            on_failed = None
            if recovery_metrics is not None:
                on_failed = (
                    lambda device: recovery_metrics.permanent_failures
                    .labels("device").inc())
            from .health import QuarantineTracker  # noqa: PLC0415

            self.health_monitor = ChipHealthMonitor(
                self.state._tpulib,
                monitor_opts,
                self._on_health_taints,
                additional_ignored=additional_ignored_health_kinds,
                quarantine=QuarantineTracker(
                    on_quarantine=on_quarantine, on_failed=on_failed),
                on_tenant_usage=self._on_tenant_usage,
                # Fleet telemetry station: samples land in the
                # process ring (/debug/telemetry), anomaly episodes
                # come back through _on_anomaly, and per-poll samples
                # through _on_chip_telemetry (gauges + quantized slice
                # attributes).
                telemetry_ring=fleetstate.default_ring(),
                on_chip_telemetry=self._on_chip_telemetry,
                on_anomaly=self._on_anomaly,
            )
        else:
            # Health monitoring off: mark every chip observably
            # unmonitored (reference taints gpu.nvidia.com/unmonitored
            # with Effect=None, device_health.go:36-40).
            from .health import TAINT_KEY_PREFIX  # noqa: PLC0415

            self._taints = {
                name: [DeviceTaint(
                    device=name,
                    key=f"{TAINT_KEY_PREFIX}/unmonitored",
                    value="true",
                    effect="",
                ).to_dict()]
                for name, dev in self.state.allocatable.items()
                if dev.kind == DeviceKind.CHIP
            }

    def start(self) -> None:
        # The reconcile sweep subsumes the stale-claim GC (it calls
        # cleanup_once() as its first pass), so only its thread runs;
        # the cleanup manager survives as the sweep's collaborator and
        # for callers driving cleanup_once() directly.
        self.reconciler.start()
        if self.health_monitor:
            self.health_monitor.start()
        # Restart reconciliation may have respawned tenancy agents and
        # resumed prepared claims before any RPC arrives -- the gauges
        # must reflect that, not 0.
        self.metrics.prepared_devices.set(self.state.prepared_device_count())
        self.metrics.tenancy_agents.set(self.state.tenancy_agent_count())
        self.publish_resources()
        # AFTER the bootstrap publish: the watcher's initial reconcile
        # converges onto any governing PartitionSet CRD (a restarted
        # plugin reaches the same carve-out set a live one holds), and
        # its apply republishes through the content-hash diff.
        if self.partition_watcher is not None:
            self.partition_watcher.start()

    def stop(self) -> None:
        if self.partition_watcher is not None:
            self.partition_watcher.stop()
        self.reconciler.stop()
        self.cleanup.stop()
        if self.health_monitor:
            self.health_monitor.stop()
        # Tenancy agents die with the plugin; prepared claims re-own
        # their dirs (and respawn agents) on the next start.
        self.state.stop()

    def _server_supports_split(self) -> bool:
        try:
            v = self.kube.server_version()
            return (int(v.get("major", "0")), int(v.get("minor", "0").rstrip("+"))) >= (1, 35)
        except Exception:  # noqa: BLE001
            return False

    # -- DRA callbacks --------------------------------------------------------

    # A multi-claim NodePrepareResources fans claims out to a small
    # thread pool: disjoint claims run the expensive middle of Prepare
    # under per-chip shard locks concurrently (device_state.py), so a
    # pod holding several claims pays ~max() instead of sum() of the
    # per-claim latencies. Bounded so a burst can't spawn a thread per
    # claim; single-claim calls skip the pool entirely.
    MAX_PARALLEL_PREPARES = 8

    def prepare_resource_claims(self, claim_refs: list) -> dict:
        """claim_refs: protobuf Claims or dicts with uid/namespace/name.
        Returns uid -> (devices, error) for the gRPC layer."""
        out = {}

        def one(ref) -> tuple[str, tuple[list, str]]:
            uid = getattr(ref, "uid", None) or ref.get("uid")
            try:
                with self.metrics.observe("NodePrepareResources"):
                    return uid, (self._prepare_one(ref), "")
            except Exception as e:  # noqa: BLE001 - wire boundary
                logger.exception("prepare failed for claim %s", uid)
                flightrecorder.default().record(
                    uid, "prepare_failed", error=str(e)[:200])
                return uid, ([], str(e))

        if len(claim_refs) <= 1:
            results = map(one, claim_refs)
        else:
            with ThreadPoolExecutor(
                min(self.MAX_PARALLEL_PREPARES, len(claim_refs)),
                thread_name_prefix="prepare",
            ) as pool:
                results = list(pool.map(one, claim_refs))
        for uid, result in results:
            out[uid] = result
        self.metrics.prepared_devices.set(self.state.prepared_device_count())
        self.metrics.tenancy_agents.set(self.state.tenancy_agent_count())
        return out

    def _prepare_one(self, ref) -> list[dict]:
        uid = getattr(ref, "uid", None) or ref.get("uid")
        namespace = getattr(ref, "namespace", None) or ref.get("namespace")
        name = getattr(ref, "name", None) or ref.get("name")
        t0 = time.monotonic()
        obj = self.kube.get(
            RESOURCE_GROUP, RESOURCE_VERSION, "resourceclaims",
            name, namespace=namespace,
        )
        if obj.get("metadata", {}).get("uid") != uid:
            raise NotFoundError(f"claim {namespace}/{name} UID mismatch")
        claim = ResourceClaim.from_dict(obj)
        # The scheduler's commit-span context rides the claim's
        # traceparent annotation: the prepare below records under the
        # SAME trace id, and the SLO prepare phase links to it.
        trace_id = tracing.trace_id_of(claim.annotations)
        self.state.prepare(claim)
        self.metrics.slo.observe("prepare", time.monotonic() - t0,
                                 trace_id)
        flightrecorder.default().record(
            uid, "prepare_done", alias=f"{namespace}/{name}",
            trace_id=trace_id,
            ms=round((time.monotonic() - t0) * 1e3, 2))
        # Group CDI ids by request for the kubelet response.
        cp = self.state.prepared_claims()[uid]
        by_request: dict[str, list] = {}
        req_of = {r.device: r.request for r in claim.results}
        for dev in cp.devices:
            by_request.setdefault(req_of.get(dev.canonical_name, ""), []).append(dev)
        devices = []
        for request, devs in by_request.items():
            for dev in devs:
                devices.append(
                    {
                        "request_names": [request] if request else [],
                        "pool_name": self.node_name,
                        "device_name": dev.canonical_name,
                        "cdi_device_ids": dev.cdi_device_ids,
                    }
                )
        logger.info(
            "prepared claim %s (%d devices) in %.1fms",
            uid, len(devices), (time.monotonic() - t0) * 1e3,
        )
        return devices

    def unprepare_resource_claims(self, claim_refs: list) -> dict:
        out = {}
        for ref in claim_refs:
            uid = getattr(ref, "uid", None) or ref.get("uid")
            try:
                with self.metrics.observe("NodeUnprepareResources"):
                    self.state.unprepare(uid)
                out[uid] = ""
            except Exception as e:  # noqa: BLE001 - wire boundary
                logger.exception("unprepare failed for claim %s", uid)
                out[uid] = str(e)
        self.metrics.prepared_devices.set(self.state.prepared_device_count())
        self.metrics.tenancy_agents.set(self.state.tenancy_agent_count())
        return out

    # -- ResourceSlice publication -------------------------------------------

    def generate_resource_slices(self) -> list[dict]:
        """Build the node's ResourceSlices.

        Legacy mode: one slice of whole chips only -- no shared counters
        or partition devices, for servers predating KEP-4815 semantics.
        Combined mode: one slice with all devices + shared counters.
        Split mode (KEP-4815, server >= 1.35): chips slice + per-partition
        slice, mirroring generateSplitResourceSlices (driver.go:190).
        resourceSliceCount is derived from the slices actually built, so
        a pool is never published incomplete (e.g. split mode with no
        partition devices publishes one slice with count 1).
        """
        host = self.state.host
        legacy = self.publication_mode == "legacy"
        devices = []
        partition_devices = []
        withheld = []
        for name, dev in sorted(self.state.allocatable.items()):
            if legacy and dev.kind not in (DeviceKind.CHIP,
                                           DeviceKind.PASSTHROUGH):
                # Partition capacity can't be expressed without shared
                # counters; legacy servers see whole chips and whole-chip
                # passthrough only (passthrough needs no counters).
                withheld.append(name)
                continue
            entry = dev.to_dra_device()
            taints = self._taints.get(name)
            if taints:
                entry["taints"] = taints
            tele = self._telemetry_attrs.get(name)
            if tele:
                entry.setdefault("attributes", {}).update(tele)
            if self._power_cap_w > 0 and dev.kind == DeviceKind.CHIP:
                entry.setdefault("attributes", {})[ATTR_POWER_CAP] = {
                    "int": self._power_cap_w}
            if not legacy:
                entry["consumesCounters"] = consumed_counters(dev, host)
            if dev.kind == DeviceKind.CHIP:
                devices.append(entry)
            else:
                partition_devices.append(entry)
        if withheld:
            logger.warning(
                "legacy publication mode withholds %d partition device(s) "
                "(no shared-counter support pre-KEP-4815): %s",
                len(withheld), ", ".join(withheld),
            )

        def slice_obj(suffix: str, devs: list[dict]) -> dict:
            spec = {
                "driver": DRIVER_NAME,
                "nodeName": self.node_name,
                "pool": {
                    "name": self.node_name,
                    "resourceSliceCount": 1,  # fixed up below
                    "generation": 1,
                },
                "perDeviceNodeSelection": False,
                "devices": devs,
            }
            if not legacy:
                spec["sharedCounters"] = shared_counter_sets(host)
            return {
                "apiVersion": f"{RESOURCE_GROUP}/{RESOURCE_VERSION}",
                "kind": "ResourceSlice",
                "metadata": {"name": f"{self.node_name}-{DRIVER_NAME}{suffix}"},
                "spec": spec,
            }

        if self.publication_mode == "split" and partition_devices:
            slices = [
                slice_obj("-chips", devices),
                slice_obj("-partitions", partition_devices),
            ]
        else:
            slices = [slice_obj("", devices + partition_devices)]
        for s in slices:
            s["spec"]["pool"]["resourceSliceCount"] = len(slices)
        return slices

    @staticmethod
    def _slice_hashes(slices: list[dict]) -> tuple:
        return tuple(sorted(
            (s["metadata"]["name"], slice_content_hash(s)) for s in slices
        ))

    def publish_resources(self) -> dict:
        """Publish the node's slices through the content-hash diff
        (pkg/sliceutil): unchanged specs cost zero kube writes, and the
        pool generation only moves when device inventory changed."""
        slices = self.generate_resource_slices()
        hashes = self._slice_hashes(slices)
        stats = publish_resource_slices(
            self.kube, slices,
            on_skip=self.metrics.slice_publish_skipped.inc,
        )
        self._published_hashes = hashes
        self._published_verified_at = time.monotonic()
        return stats

    def apply_partition_set(self, partition_set) -> dict:
        """Profile-guided re-plan: swap the desired partition layout
        and republish. Partition churn rides the content-hash diff --
        only the slices whose device inventory actually changed are
        rewritten (and a converged re-apply costs zero writes)."""
        self.state.apply_partition_set(partition_set)
        return self.publish_resources()

    def apply_prewarm(self, hints: dict) -> int:
        """Predictive pre-warming: converge the partition engine's
        warm carve-out set onto the forecaster's hint (the winning
        PartitionSet CRD's prewarm annotation -- pkg/autoscale). No
        republish: carve-out realization changes no published device,
        so a hint application costs ZERO kube calls. Returns
        carve-outs created."""
        if self.state.partition_engine is None:
            return 0
        return self.state.partition_engine.set_prewarm(hints or {})

    # -- health ---------------------------------------------------------------

    def _on_tenant_usage(self, usage) -> None:
        """Health-poll telemetry -> the live tenant-demand store: each
        tpulib per-tenant HBM/core sample lands in the
        TenantProfileStore the MISO sizing policy reads, replacing
        static-file-only demand (ROADMAP item 1 follow-up)."""
        for u in usage:
            self.tenant_profiles.record(u.tenant, u.hbm_bytes,
                                        cores=u.cores)

    def _on_health_taints(self, taints: list[DeviceTaint]) -> None:
        """Reconcile device taints and republish (driver.go:496-566).

        The health monitor reports the FULL current taint list every
        poll, so steady state arrives here once per poll interval with
        nothing changed -- short-circuit on the published content hash
        and touch the apiserver ZERO times (no list, no writes)."""
        new: dict[str, list[dict]] = {}
        for t in taints:
            new.setdefault(t.device, []).append(t.to_dict())
        self._taints = new
        self.metrics.set_taints(taints)
        self._republish_reconciled()

    def _republish_reconciled(self) -> None:
        """Republish through the content-hash short-circuit: ZERO kube
        calls (no list, no writes) when the generated slices hash to
        what was last published and the memo is fresh. Shared by the
        health-taint and telemetry-attribute reconcile paths -- both
        arrive once per poll with, in steady state, nothing changed."""
        slices = self.generate_resource_slices()
        hashes = self._slice_hashes(slices)
        fresh = (time.monotonic() - self._published_verified_at
                 < self._publish_recheck_s)
        if hashes == self._published_hashes and fresh:
            self.metrics.slice_publish_skipped.inc(len(slices))
            return
        # Changed content, or the periodic live recheck: the publish
        # diff lists the live pool and writes only what differs (zero
        # writes when still converged -- but it repairs slices another
        # actor deleted or mutated behind the memo).
        try:
            publish_resource_slices(
                self.kube, slices,
                on_skip=self.metrics.slice_publish_skipped.inc,
            )
            self._published_hashes = hashes
            self._published_verified_at = time.monotonic()
        except Exception:  # noqa: BLE001 - known reference gap: no retry
            logger.exception("republish after health event failed")

    # -- fleet telemetry ------------------------------------------------------

    def _on_chip_telemetry(self, samples) -> None:
        """Health-poll telemetry -> per-chip gauges + quantized slice
        attributes. Quantization (TELEMETRY_*_STEP) keeps steady-state
        samples hashing identically, so the republish below
        short-circuits to zero kube calls until a signal actually
        moves a step."""
        hbm_cap = max(self.state.host.hbm_bytes_per_chip, 1)
        attrs: dict[str, dict] = {}
        self.metrics.telemetry.prune_absent(s.chip for s in samples)
        for s in samples:
            self.metrics.telemetry.observe_sample(s)
            name = chip_name(s.chip)
            if name not in self.state.allocatable:
                continue

            def q(val: float, step: int) -> int:
                return int(round(float(val) / step) * step)

            attrs[name] = {
                fleetstate.ATTR_POWER: {
                    "int": q(s.power_watts, TELEMETRY_POWER_STEP_W)},
                fleetstate.ATTR_TEMP: {
                    "int": q(s.temp_celsius, TELEMETRY_TEMP_STEP_C)},
                fleetstate.ATTR_DUTY: {
                    "int": q(s.duty_cycle * 100,
                             TELEMETRY_DUTY_STEP_PCT)},
                fleetstate.ATTR_HBM: {
                    "int": q(s.hbm_used_bytes * 100 / hbm_cap,
                             TELEMETRY_HBM_STEP_PCT)},
                fleetstate.ATTR_ICI_ERR: {
                    "int": q(s.ici_link_errors, TELEMETRY_ICI_STEP)},
            }
        if not self._telemetry_attrs_enabled:
            return
        # REPLACE semantics: a chip absent from this sample set (its
        # sensor path died) drops its attributes instead of publishing
        # a frozen-but-plausible last reading forever.
        if attrs == self._telemetry_attrs:
            # Quantization held every value in place: the slice spec
            # cannot have changed, so skip even the generate+hash.
            # (This dict compare IS the telemetry steady state -- the
            # <=5% overhead gate depends on it. Externally mutated
            # slices still self-heal via the health path's periodic
            # TPU_DRA_PUBLISH_RECHECK_S live recheck.)
            return
        self._telemetry_attrs = attrs
        self._republish_reconciled()

    def _on_anomaly(self, detections) -> None:
        """Anomaly episode rising edges -> counter + flight record +
        deduped Warning Event on the Node. The quarantine escalation
        needs no wiring here: the detector's taints ride the health
        poll's taint list straight into the QuarantineTracker."""
        for det in detections:
            self.metrics.telemetry.inc_anomaly(det.kind)
            flightrecorder.default().record(
                det.device, "anomaly", kind=det.kind,
                node=self.node_name, **det.detail)
            logger.warning(
                "telemetry anomaly on %s/%s: %s %s", self.node_name,
                det.device, det.kind, det.detail)
            emit_warning_event(
                self.kube,
                # Deterministic name = create-once per (node, device,
                # kind): a repeat episode of the same anomaly hits 409
                # and is swallowed.
                event_name=(f"{self.node_name}.{det.device}."
                            f"{det.kind.replace('_', '-')}"),
                namespace="default",
                reason="TelemetryAnomaly",
                message=(
                    f"{det.kind} detected on {det.device} "
                    f"(node {self.node_name}): {det.detail}; "
                    "time-series at /debug/telemetry on the node "
                    "plugin, bundle via python -m "
                    "k8s_dra_driver_gpu_tpu.pkg.doctor"),
                involved_kind="Node", involved_name=self.node_name,
                component="tpu-dra-kubelet-plugin")
