"""Device model: typed info per allocatable device -> DRA Device dicts.

Reference: cmd/gpu-kubelet-plugin/deviceinfo.go (GpuInfo/MigDeviceInfo/
VfioDeviceInfo -> resourceapi.Device with attributes at :152-199) and
allocatable.go (AllocatableDevice tagged union :48, PerGPUAllocatable-
Devices :43, taint bookkeeping :319-328).

Attributes published per device (CEL-selectable by schedulers):
  uuid, platform, acceleratorType, topology (full-slice grid),
  iciX/iciY/iciZ (chip coords), numaNode, pciBdf, workerId, numHosts,
  profile/placement for sub-slices. Capacities: hbmBytes, tensorCores.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum

from ..pkg.partition.spec import PartitionProfile, partition_device_name
from ..tpulib.binding import TpuChip, TpuHostInfo
from .subslice import SubSliceSpecTuple, chip_name


class DeviceKind(str, Enum):
    CHIP = "chip"
    SUBSLICE_STATIC = "subslice-static"
    SUBSLICE_DYNAMIC = "subslice-dynamic"
    PASSTHROUGH = "passthrough"
    # PartitionSet-desired tenant partition (pkg/partition): a dynamic
    # carve-out sold as one or more tenant slots with a budgeted HBM
    # share. Realized/retired on demand by the partition engine.
    PARTITION = "partition"


@dataclass(frozen=True)
class ChipInfo:
    chip: TpuChip
    host: TpuHostInfo

    @property
    def canonical_name(self) -> str:
        return chip_name(self.chip.index)

    def attributes(self) -> dict:
        x, y, z = self.chip.ici_coords
        return {
            "uuid": self.chip.uuid,
            "platform": self.host.platform,
            "acceleratorType": self.host.accelerator_type,
            "topology": self.host.topology,
            "iciX": x,
            "iciY": y,
            "iciZ": z,
            "numaNode": self.chip.numa_node,
            "pciBdf": self.chip.pci_bdf,
            "workerId": self.host.worker_id,
            "numHosts": self.host.num_hosts,
            "coresPerChip": self.host.cores_per_chip,
        }

    def capacities(self) -> dict:
        return {
            "hbmBytes": self.host.hbm_bytes_per_chip,
            "tensorCores": self.host.cores_per_chip,
        }


@dataclass(frozen=True)
class SubSliceInfo:
    spec: SubSliceSpecTuple
    host: TpuHostInfo
    dynamic: bool  # True: created at Prepare; False: pre-carved static

    @property
    def canonical_name(self) -> str:
        return self.spec.canonical_name()

    @property
    def chips(self) -> int:
        return 0 if self.spec.is_core_level else len(
            self.spec.chip_positions(self.host)
        )

    @property
    def cores(self) -> int:
        return len(self.spec.core_indices(self.host))

    @property
    def hbm_bytes(self) -> int:
        per_core = self.host.hbm_bytes_per_chip // self.host.cores_per_chip
        return per_core * self.cores

    def attributes(self) -> dict:
        return {
            "platform": self.host.platform,
            "acceleratorType": self.host.accelerator_type,
            "topology": self.host.topology,
            "profile": self.spec.profile,
            "placement": self.spec.placement,
            "parentChip": (
                self.spec.parent_chip if self.spec.is_core_level else -1
            ),
            "workerId": self.host.worker_id,
            "dynamic": self.dynamic,
        }

    def capacities(self) -> dict:
        return {"hbmBytes": self.hbm_bytes, "tensorCores": self.cores}


@dataclass(frozen=True)
class PartitionInfo:
    """A tenant partition: a PartitionSet profile applied to one
    backing carve-out placement (pkg/partition/spec.py).

    Published capacities are PER TENANT SLOT: ``hbmBytes`` is the
    tenant's HBM budget (carve-out HBM x hbmFraction / maxTenants) and
    ``tensorCores`` the tenant's core share as a milli quantity -- the
    same virtual-capacity split the device's KEP-4815
    ``consumesCounters`` encode, so N slot allocations together consume
    exactly the backing carve-out's budget."""

    profile: PartitionProfile
    spec: SubSliceSpecTuple  # the backing carve-out
    host: TpuHostInfo
    placement: int  # index within the profile's placement list

    @property
    def canonical_name(self) -> str:
        return partition_device_name(self.profile.name, self.placement)

    @property
    def cores(self) -> int:
        return len(self.spec.core_indices(self.host))

    @property
    def carve_hbm_bytes(self) -> int:
        per_core = (self.host.hbm_bytes_per_chip
                    // self.host.cores_per_chip)
        return per_core * self.cores

    @property
    def tenant_hbm_bytes(self) -> int:
        """Per-tenant HBM budget/ceiling."""
        return int(self.carve_hbm_bytes * self.profile.hbm_fraction
                   ) // self.profile.max_tenants

    @property
    def tenant_core_milli(self) -> int:
        """Per-tenant core share PER CORE of the backing carve-out, in
        milli-cores (the virtual-capacity multiplier)."""
        return 1000 // self.profile.max_tenants

    @property
    def oversubscribed(self) -> bool:
        return self.profile.max_tenants > 1

    def attributes(self) -> dict:
        return {
            "platform": self.host.platform,
            "acceleratorType": self.host.accelerator_type,
            "topology": self.host.topology,
            "profile": self.profile.name,
            "subslice": self.profile.subslice,
            "placement": self.placement,
            "parentChip": (
                self.spec.parent_chip if self.spec.is_core_level else -1
            ),
            "workerId": self.host.worker_id,
            "partition": True,
            # > 1 marks a shared device the scheduler may allocate to
            # several tenant claims (slot-aware AllocationState).
            "oversubscribeSlots": self.profile.max_tenants,
        }

    def capacities(self) -> dict:
        caps: dict = {"hbmBytes": self.tenant_hbm_bytes}
        if self.profile.max_tenants > 1:
            caps["tensorCores"] = (
                f"{(self.cores * 1000) // self.profile.max_tenants}m")
        else:
            caps["tensorCores"] = self.cores
        return caps


@dataclass(frozen=True)
class PassthroughInfo:
    """A chip surfaced for vfio passthrough (VfioDeviceInfo analog)."""

    chip: TpuChip
    host: TpuHostInfo
    iommu_group: int = -1

    @property
    def canonical_name(self) -> str:
        return f"{chip_name(self.chip.index)}-passthrough"

    def attributes(self) -> dict:
        return {
            "uuid": self.chip.uuid,
            "platform": self.host.platform,
            "pciBdf": self.chip.pci_bdf,
            "iommuGroup": self.iommu_group,
            "passthrough": True,
        }

    def capacities(self) -> dict:
        return {"hbmBytes": self.host.hbm_bytes_per_chip}


@dataclass
class AllocatableDevice:
    """Tagged union over everything this node can allocate
    (allocatable.go:48)."""

    kind: DeviceKind
    chip: ChipInfo | None = None
    subslice: SubSliceInfo | None = None
    passthrough: PassthroughInfo | None = None
    partition: PartitionInfo | None = None
    # DRA device taints currently applied (health events -> taints).
    taints: list[dict] = field(default_factory=list)

    @property
    def canonical_name(self) -> str:
        return self._info.canonical_name

    @property
    def _info(self):
        return (self.chip or self.subslice or self.passthrough
                or self.partition)

    def to_dra_device(self) -> dict:
        """-> a resource.k8s.io Device entry for a ResourceSlice."""
        info = self._info
        attrs = {}
        for key, val in info.attributes().items():
            if isinstance(val, bool):
                attrs[key] = {"bool": val}
            elif isinstance(val, int):
                attrs[key] = {"int": val}
            else:
                attrs[key] = {"string": str(val)}
        caps = {
            key: {"value": str(val)} for key, val in info.capacities().items()
        }
        dev: dict = {
            "name": self.canonical_name,
            "attributes": attrs,
            "capacity": caps,
        }
        if self.taints:
            dev["taints"] = list(self.taints)
        return dev


# chip index -> {canonical name -> AllocatableDevice}; mirrors
# PerGPUAllocatableDevices (allocatable.go:43). Host-scoped (multi-chip)
# sub-slices key under their lowest chip index.
PerChipAllocatableDevices = dict[int, dict[str, AllocatableDevice]]
