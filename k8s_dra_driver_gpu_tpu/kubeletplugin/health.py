"""Chip health monitoring: tpulib health events -> DRA device taints.

Reference: cmd/gpu-kubelet-plugin/device_health.go -- NVML event-set
monitor mapping XID/GPU-lost events to devices (:101), a skip-list of
benign events plus user-supplied ignores (:394-443), events becoming
DeviceTaints (keys gpu.nvidia.com/xid|gpu-lost, :36-40) consumed by the
driver to taint + republish ResourceSlices (driver.go:496-566).

TPU translation: tpulib health kinds (hbm_uncorrectable, ici_link_down,
chip_lost, thermal, ...) map to taints under tpu.dra.dev/. Non-fatal
kinds produce Effect=None taints (observability without eviction),
mirroring the reference's Option-A schema.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Callable

from ..tpulib.binding import EnumerateOptions, HealthEvent
from .subslice import chip_name

logger = logging.getLogger(__name__)

TAINT_KEY_PREFIX = "tpu.dra.dev"

# Benign kinds never surfaced as NoSchedule/NoExecute (skip-list analog,
# device_health.go:394-443).
DEFAULT_IGNORED_KINDS = frozenset({"thermal_notice", "clock_throttle"})

# Reference polls NVML events with 5000ms waits; the env override lets
# operators (and the republish-storm e2e) tighten detection latency.
from ..pkg import positive_float_env

POLL_INTERVAL_S = positive_float_env(
    "TPU_DRA_HEALTH_POLL_S", default=5.0, floor=0.05)


@dataclass(frozen=True)
class DeviceTaint:
    device: str  # canonical device name
    key: str
    value: str
    effect: str  # NoSchedule | NoExecute | None ("" = observe only)

    def to_dict(self) -> dict:
        d = {"key": self.key, "value": self.value}
        if self.effect:
            d["effect"] = self.effect
        return d


def health_event_to_taints(
    event: HealthEvent,
    ignored_kinds: frozenset[str] = DEFAULT_IGNORED_KINDS,
) -> list[DeviceTaint]:
    """Map one health event to taints on the affected chip."""
    if event.kind in ignored_kinds:
        return []
    effect = "NoExecute" if event.fatal else ""
    return [
        DeviceTaint(
            device=chip_name(event.chip),
            key=f"{TAINT_KEY_PREFIX}/{event.kind}",
            value="true",
            effect=effect,
        )
    ]


class ChipHealthMonitor:
    """Polls tpulib health and pushes taint updates to a callback.

    The callback receives the full current taint list (per poll), so the
    consumer can reconcile (add + clear) rather than accumulate.
    """

    def __init__(
        self,
        tpulib,
        opts: EnumerateOptions,
        on_taints: Callable[[list[DeviceTaint]], None],
        ignored_kinds: frozenset[str] = DEFAULT_IGNORED_KINDS,
        additional_ignored: tuple[str, ...] = (),
        poll_interval: float = POLL_INTERVAL_S,
    ):
        self._tpulib = tpulib
        self._opts = opts
        self._on_taints = on_taints
        self._ignored = frozenset(ignored_kinds) | frozenset(additional_ignored)
        self._interval = poll_interval
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="chip-health", daemon=True
        )
        self._last: list[DeviceTaint] | None = None

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread.ident is not None:  # join only a started thread
            self._thread.join(timeout=self._interval + 1)

    def poll_once(self) -> list[DeviceTaint]:
        events = self._tpulib.health(self._opts)
        taints: list[DeviceTaint] = []
        for ev in events:
            taints.extend(health_event_to_taints(ev, self._ignored))
        return taints

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                taints = self.poll_once()
            except Exception:  # noqa: BLE001 - monitor must survive
                logger.exception("health poll failed")
                continue
            if taints != self._last:
                self._last = taints
                self._on_taints(taints)
