"""Chip health monitoring: tpulib health events -> DRA device taints.

Reference: cmd/gpu-kubelet-plugin/device_health.go -- NVML event-set
monitor mapping XID/GPU-lost events to devices (:101), a skip-list of
benign events plus user-supplied ignores (:394-443), events becoming
DeviceTaints (keys gpu.nvidia.com/xid|gpu-lost, :36-40) consumed by the
driver to taint + republish ResourceSlices (driver.go:496-566).

TPU translation: tpulib health kinds (hbm_uncorrectable, ici_link_down,
chip_lost, thermal, ...) map to taints under tpu.dra.dev/. Non-fatal
kinds produce Effect=None taints (observability without eviction),
mirroring the reference's Option-A schema.

Quarantine (the flapping-chip escalation the reference lacks): a chip
that keeps emitting NON-FATAL events -- healthy, sick, healthy, sick --
never trips the fatal path, yet every workload placed on it eats the
flap. The QuarantineTracker counts non-fatal events per chip inside a
sliding window; at the threshold it escalates to a
``tpu.dra.dev/degraded`` NoSchedule taint (published through the same
reconcile-and-republish pipeline), and only releases after the chip has
stayed clean for a hysteresis period -- so a flapper can't oscillate in
and out of the schedulable pool at poll frequency.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable

from ..pkg import anomaly as anomaly_mod
from ..pkg import faults, fleetstate
from ..tpulib.binding import EnumerateOptions, HealthEvent
from .subslice import chip_name

logger = logging.getLogger(__name__)

TAINT_KEY_PREFIX = "tpu.dra.dev"
QUARANTINE_KIND = "degraded"

# Benign kinds never surfaced as NoSchedule/NoExecute (skip-list analog,
# device_health.go:394-443).
DEFAULT_IGNORED_KINDS = frozenset({"thermal_notice", "clock_throttle"})

# Reference polls NVML events with 5000ms waits; the env override lets
# operators (and the republish-storm e2e) tighten detection latency.
from ..pkg import positive_float_env

POLL_INTERVAL_S = positive_float_env(
    "TPU_DRA_HEALTH_POLL_S", default=5.0, floor=0.05)
# A failing poll (tpulib enumeration error, callback bug) backs off
# exponentially up to this cap instead of hammering a sick library --
# and NEVER kills the poll thread.
POLL_BACKOFF_MAX_S = positive_float_env(
    "TPU_DRA_HEALTH_BACKOFF_MAX_S", default=60.0, floor=0.05)

# Quarantine knobs: N non-fatal events within the window escalate; the
# chip must then stay clean for the hysteresis period to untaint.
QUARANTINE_EVENTS = int(positive_float_env(
    "TPU_DRA_QUARANTINE_EVENTS", default=3, floor=1))
QUARANTINE_WINDOW_S = positive_float_env(
    "TPU_DRA_QUARANTINE_WINDOW_S", default=300.0, floor=0.05)
QUARANTINE_HYSTERESIS_S = positive_float_env(
    "TPU_DRA_QUARANTINE_HYSTERESIS_S", default=600.0, floor=0.05)

# Permanent-failure escalation (pkg/recovery.py consumes the taint): a
# chip that earns quarantine this many SEPARATE times has proven the
# hysteresis release wrong repeatedly -- it is hardware going bad, not
# a transient. It escalates to a sticky `tpu.dra.dev/failed` NoExecute
# taint that never releases (only a plugin restart after repair, or an
# operator clearing the knob, brings the chip back).
FAILED_KIND = "failed"
QUARANTINE_FATAL_ESCALATIONS = int(positive_float_env(
    "TPU_DRA_RECOVERY_FATAL_QUARANTINES", default=3, floor=1))


@dataclass(frozen=True)
class DeviceTaint:
    device: str  # canonical device name
    key: str
    value: str
    effect: str  # NoSchedule | NoExecute | None ("" = observe only)

    def to_dict(self) -> dict:
        d = {"key": self.key, "value": self.value}
        if self.effect:
            d["effect"] = self.effect
        return d


def health_event_to_taints(
    event: HealthEvent,
    ignored_kinds: frozenset[str] = DEFAULT_IGNORED_KINDS,
) -> list[DeviceTaint]:
    """Map one health event to taints on the affected chip."""
    if event.kind in ignored_kinds:
        return []
    effect = "NoExecute" if event.fatal else ""
    return [
        DeviceTaint(
            device=chip_name(event.chip),
            key=f"{TAINT_KEY_PREFIX}/{event.kind}",
            value="true",
            effect=effect,
        )
    ]


class QuarantineTracker:
    """Escalates flapping chips to NoSchedule quarantine, with
    hysteresis on the way back.

    State machine per device:
      healthy --(>= threshold non-fatal events inside window)--> quarantined
      quarantined --(clean for >= hysteresis)--> healthy
      quarantined --(earned quarantine >= fatal_after times)--> FAILED

    FAILED is terminal and sticky (``tpu.dra.dev/failed`` NoExecute):
    a chip that keeps cycling healthy -> quarantined -> "healed" ->
    quarantined has proven the hysteresis release wrong repeatedly --
    that is hardware dying, and pkg/recovery.py escalates its claims
    to PermanentFailure + eviction off the published taint.

    ``observe(taints)`` is called once per poll with the RAW taint list
    and returns the quarantine + failure taints to merge in.
    ``on_quarantine`` / ``on_failed`` fire once per escalation
    (metrics hooks)."""

    def __init__(
        self,
        threshold: int = QUARANTINE_EVENTS,
        window_s: float = QUARANTINE_WINDOW_S,
        hysteresis_s: float = QUARANTINE_HYSTERESIS_S,
        on_quarantine: Callable[[str], None] | None = None,
        fatal_after: int = QUARANTINE_FATAL_ESCALATIONS,
        on_failed: Callable[[str], None] | None = None,
        clock=time.monotonic,
    ):
        self.threshold = max(1, int(threshold))
        self.window_s = window_s
        self.hysteresis_s = hysteresis_s
        self.on_quarantine = on_quarantine
        self.fatal_after = max(1, int(fatal_after))
        self.on_failed = on_failed
        self._clock = clock
        # device -> recent healthy->sick TRANSITION timestamps
        # (window-pruned). Transitions, not per-poll presence: tpulib
        # reports a chip's CURRENT condition every poll, so a single
        # steady non-fatal warning would otherwise hit the threshold in
        # `threshold` polls (~15s) -- but a steady condition is exactly
        # the "observability without eviction" case; only FLAPPING
        # earns quarantine.
        self._events: dict[str, list[float]] = {}
        # Previous poll's sick set (the edge detector).
        self._prev_flapping: set[str] = set()
        # device -> timestamp of the LAST observed event while
        # quarantined (hysteresis restarts on every flap)
        self._quarantined: dict[str, float] = {}
        # device -> how many SEPARATE times it earned quarantine; at
        # fatal_after it escalates to the sticky failed set.
        self._escalations: dict[str, int] = {}
        self._failed: set[str] = set()
        self.total_quarantines = 0
        self.total_failures = 0

    @property
    def quarantined(self) -> frozenset[str]:
        return frozenset(self._quarantined)

    @property
    def failed(self) -> frozenset[str]:
        """Devices escalated to sticky permanent failure."""
        return frozenset(self._failed)

    def mark_failed(self, device: str) -> None:
        """Declare a device permanently failed directly (the fatal-
        event and reconcile-sweep escalation path: bypasses the
        quarantine counting entirely)."""
        if device in self._failed:
            return
        self._failed.add(device)
        self._quarantined.pop(device, None)
        self._events.pop(device, None)
        self.total_failures += 1
        logger.error(
            "chip %s declared PERMANENTLY FAILED (sticky %s/%s "
            "NoExecute taint; claims on it will be evicted)",
            device, TAINT_KEY_PREFIX, FAILED_KIND,
        )
        if self.on_failed is not None:
            try:
                self.on_failed(device)
            except Exception:  # noqa: BLE001 - metrics hook
                logger.exception("failure hook failed")

    def observe(self, taints: list[DeviceTaint]) -> list[DeviceTaint]:
        now = self._clock()
        flapping = {
            t.device for t in taints
            # Non-fatal, non-quarantine signals only: fatal events carry
            # their own NoExecute taint, and our own degraded taint must
            # not feed back into the event count. A permanently failed
            # device is past all of this bookkeeping.
            if not t.effect and t.device not in self._failed
            and t.key != f"{TAINT_KEY_PREFIX}/{QUARANTINE_KIND}"
        }
        for device in flapping:
            if device in self._quarantined:
                # ANY presence (steady or edge) restarts hysteresis: a
                # chip must be fully clean to earn release.
                self._quarantined[device] = now
                continue
            if device in self._prev_flapping:
                continue  # steady condition, not a new flap
            events = self._events.setdefault(device, [])
            events.append(now)
        self._prev_flapping = flapping
        # Window prune + escalation.
        for device, events in list(self._events.items()):
            events[:] = [t for t in events if now - t <= self.window_s]
            if not events:
                del self._events[device]
                continue
            if len(events) >= self.threshold and \
                    device not in self._quarantined:
                self._quarantined[device] = now
                del self._events[device]
                self.total_quarantines += 1
                logger.warning(
                    "quarantining %s: %d non-fatal health events within "
                    "%.0fs (NoSchedule until clean for %.0fs)",
                    device, self.threshold, self.window_s,
                    self.hysteresis_s,
                )
                if self.on_quarantine is not None:
                    try:
                        self.on_quarantine(device)
                    except Exception:  # noqa: BLE001 - metrics hook
                        logger.exception("quarantine hook failed")
                # A chip earning quarantine for the Nth time has blown
                # through the hysteresis release N-1 times: escalate
                # from quarantine to declared permanent failure.
                n = self._escalations.get(device, 0) + 1
                self._escalations[device] = n
                if n >= self.fatal_after:
                    self.mark_failed(device)
        # Hysteresis release: clean for the full period.
        for device, last_event in list(self._quarantined.items()):
            if device not in flapping and \
                    now - last_event >= self.hysteresis_s:
                del self._quarantined[device]
                logger.warning(
                    "releasing %s from quarantine (clean for %.0fs)",
                    device, now - last_event,
                )
        return [
            DeviceTaint(
                device=device,
                key=f"{TAINT_KEY_PREFIX}/{QUARANTINE_KIND}",
                value="true",
                effect="NoSchedule",
            )
            for device in sorted(self._quarantined)
        ] + [
            # Sticky: a failed chip stays NoExecute-tainted every poll
            # until the plugin restarts after physical repair.
            DeviceTaint(
                device=device,
                key=f"{TAINT_KEY_PREFIX}/{FAILED_KIND}",
                value="true",
                effect="NoExecute",
            )
            for device in sorted(self._failed)
        ]


class ChipHealthMonitor:
    """Polls tpulib health and pushes taint updates to a callback.

    The callback receives the full current taint list (per poll) --
    raw event taints plus quarantine escalations -- so the consumer can
    reconcile (add + clear) rather than accumulate.
    """

    def __init__(
        self,
        tpulib,
        opts: EnumerateOptions,
        on_taints: Callable[[list[DeviceTaint]], None],
        ignored_kinds: frozenset[str] = DEFAULT_IGNORED_KINDS,
        additional_ignored: tuple[str, ...] = (),
        poll_interval: float = POLL_INTERVAL_S,
        quarantine: QuarantineTracker | None = None,
        on_quarantine: Callable[[str], None] | None = None,
        on_tenant_usage: Callable[[tuple], None] | None = None,
        telemetry_ring=None,  # pkg.fleetstate.TelemetryRing | None
        anomaly_detector=None,  # pkg.anomaly.AnomalyDetector | None
        on_chip_telemetry: Callable[[tuple], None] | None = None,
        on_anomaly: Callable[[list], None] | None = None,
    ):
        self._tpulib = tpulib
        self._opts = opts
        self._on_taints = on_taints
        # Live per-tenant HBM/core telemetry (tpulib.tenant_usage):
        # sampled on the SAME poll cadence as health and handed to the
        # consumer (the driver feeds its TenantProfileStore, the MISO
        # sizing input). None = telemetry off; a tpulib without the
        # seam degrades to no samples.
        self._on_tenant_usage = on_tenant_usage
        # Fleet telemetry (tpulib.chip_telemetry, the node-collector
        # half of the telemetry plane): per-chip power/thermal/HBM/
        # duty samples ride the SAME poll cadence, land in the bounded
        # ring served at /debug/telemetry, run through the anomaly
        # detectors, and reach the driver via on_chip_telemetry
        # (metric gauges + quantized slice attributes) / on_anomaly
        # (Warning Events + counters + flight records). Anomaly taints
        # feed the quarantine tracker exactly like raw health events.
        # TPU_DRA_TELEMETRY=0 turns the whole station off.
        self._telemetry_enabled = fleetstate.telemetry_enabled()
        self.telemetry_ring = telemetry_ring
        self.anomaly = anomaly_detector
        if self._telemetry_enabled and self.anomaly is None and \
                (on_anomaly is not None or telemetry_ring is not None):
            self.anomaly = anomaly_mod.AnomalyDetector(
                chip_name=chip_name)
        self._on_chip_telemetry = on_chip_telemetry
        self._on_anomaly = on_anomaly
        self._ignored = frozenset(ignored_kinds) | frozenset(additional_ignored)
        self._interval = poll_interval
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="chip-health", daemon=True
        )
        self._last: list[DeviceTaint] | None = None
        self.quarantine = quarantine or QuarantineTracker(
            on_quarantine=on_quarantine)
        self.consecutive_failures = 0

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread.ident is not None:  # join only a started thread
            self._thread.join(timeout=self._interval + 1)

    def poll_once(self) -> list[DeviceTaint]:
        # Fault seam: the chaos suite's flapping-chip and sick-tpulib
        # scenarios act here (error mode must NOT kill the poll thread;
        # see _run's backoff).
        faults.fault_point("health.poll")
        events = self._tpulib.health(self._opts)
        taints: list[DeviceTaint] = []
        for ev in events:
            taints.extend(health_event_to_taints(ev, self._ignored))
        return taints

    def poll_and_reconcile(self) -> list[DeviceTaint]:
        """One poll + telemetry sample + quarantine pass: the merged
        taint list the callback sees (also the direct-drive entry for
        tests/bench). Anomaly taints (non-fatal, observe-only) merge
        BEFORE the quarantine pass, so a flapping anomaly escalates
        through the same transition counting as a flapping health
        event."""
        taints = self.poll_once()
        try:
            # Telemetry must never poison the health poll: a broken
            # seam only loses samples (and their anomaly taints).
            self.sample_chip_telemetry()
        except Exception:  # noqa: BLE001 - telemetry best-effort
            logger.exception("chip-telemetry sample failed")
        if self.anomaly is not None:
            taints = taints + self.anomaly.taints(
                DeviceTaint, TAINT_KEY_PREFIX)
        return taints + self.quarantine.observe(taints)

    def sample_chip_telemetry(self) -> tuple:
        """One per-chip telemetry sample through the tpulib seam:
        ring append, anomaly fold, consumer callbacks. Returns the
        samples (also the direct-drive entry for tests/bench). A
        tpulib predating the seam, TPU_DRA_TELEMETRY=0, or no wiring
        at all is a no-op."""
        if not self._telemetry_enabled:
            return ()
        fn = getattr(self._tpulib, "chip_telemetry", None)
        if fn is None:
            return ()
        if self.telemetry_ring is None and self.anomaly is None and \
                self._on_chip_telemetry is None:
            return ()
        samples = tuple(fn(self._opts) or ())
        if self.telemetry_ring is not None:
            for s in samples:
                self.telemetry_ring.record_sample(s)
        if self.anomaly is not None:
            detections = self.anomaly.observe(samples)
            if detections and self._on_anomaly is not None:
                try:
                    self._on_anomaly(detections)
                except Exception:  # noqa: BLE001 - consumer hook
                    logger.exception("anomaly hook failed")
        if self._on_chip_telemetry is not None:
            # Delivered even when EMPTY: the consumer drops stale
            # slice attributes for chips that stopped reporting.
            self._on_chip_telemetry(samples)
        return samples

    def sample_telemetry(self) -> tuple:
        """One per-tenant usage sample through the tpulib seam,
        delivered to ``on_tenant_usage``. Returns the samples (also
        the direct-drive entry for tests). A tpulib predating the
        seam, or no consumer, is a no-op."""
        if self._on_tenant_usage is None:
            return ()
        fn = getattr(self._tpulib, "tenant_usage", None)
        if fn is None:
            return ()
        usage = tuple(fn(self._opts) or ())
        if usage:
            self._on_tenant_usage(usage)
        return usage

    def _backoff(self) -> float:
        """Current sleep: the base interval, doubled per consecutive
        failure (capped) so a dying tpulib isn't hammered at full poll
        rate forever."""
        if self.consecutive_failures == 0:
            return self._interval
        return min(
            self._interval * (2 ** min(self.consecutive_failures, 16)),
            max(POLL_BACKOFF_MAX_S, self._interval),
        )

    def _run(self) -> None:
        while not self._stop.wait(self._backoff()):
            # The WHOLE body is guarded: an exception from tpulib
            # enumeration -- or from the consumer's callback -- logs and
            # backs off instead of silently killing the poll thread (a
            # dead monitor reads as "all healthy" forever).
            try:
                taints = self.poll_and_reconcile()
            except Exception:  # noqa: BLE001 - monitor must survive
                self.consecutive_failures += 1
                logger.exception(
                    "health poll failed (%d consecutive; next attempt "
                    "in %.1fs)", self.consecutive_failures,
                    self._backoff())
                continue
            self.consecutive_failures = 0
            try:
                # Telemetry rides the health cadence but must never
                # poison it: a broken usage seam only loses samples.
                self.sample_telemetry()
            except Exception:  # noqa: BLE001 - telemetry best-effort
                logger.exception("tenant-usage sample failed")
            if taints != self._last:
                self._last = taints
                try:
                    self._on_taints(taints)
                except Exception:  # noqa: BLE001 - consumer bug
                    # Re-deliver next poll: _last must not claim this
                    # list was delivered.
                    self._last = None
                    self.consecutive_failures += 1
                    logger.exception("health taint callback failed")
