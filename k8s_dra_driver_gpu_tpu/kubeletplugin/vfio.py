"""VFIO passthrough: hand a whole TPU chip to a guest/userspace driver.

Reference: cmd/gpu-kubelet-plugin/vfio-device.go -- VfioPciManager.
Configure (:145): wait device free, unbind from the native driver, bind
to vfio-pci via driver_override sysfs writes; Unconfigure (:189) reverses
and rediscovers. vfio-cdi.go exposes /dev/vfio/<group> (legacy) or
/dev/vfio/devices/* (iommufd).

TPU translation: same sysfs mechanics against the TPU PCI function. All
paths are rooted at a configurable sys_root/dev_root so the whole flow
runs against a fake sysfs tree in tests (and mock mode).
"""

from __future__ import annotations

import logging
import os

from ..api.configs import PassthroughConfig
from ..pkg.flock import Flock
from .cdi import ContainerEdits

logger = logging.getLogger(__name__)

VFIO_DRIVER = "vfio-pci"
NATIVE_DRIVER = "tpu"  # the in-kernel accel driver to rebind on release


class VfioRegistry:
    """Crash-persistent record of functions we rebound to vfio-pci (and
    their original drivers), written BEFORE the rebind so startup
    reconciliation can always undo an orphaned rebind -- the same role
    the SubSliceRegistry plays for dynamic carve-outs."""

    def __init__(self, root: str):
        os.makedirs(root, exist_ok=True)
        self._path = os.path.join(root, "vfio.json")
        # Flock-guarded read-modify-write: with the sharded prepare
        # pipeline, disjoint passthrough claims rebind concurrently
        # (across threads AND processes during upgrade handover) and
        # all land in this one file -- same pattern as SubSliceRegistry.
        self._lock = Flock(self._path + ".lock")

    def list(self) -> dict[str, dict]:
        import json  # noqa: PLC0415

        try:
            with open(self._path, encoding="utf-8") as f:
                return json.load(f)
        except (FileNotFoundError, ValueError):
            return {}

    def _write(self, entries: dict[str, dict]) -> None:
        import json  # noqa: PLC0415

        tmp = self._path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(entries, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path)

    def add(self, pci_bdf: str, native_driver: str | None) -> None:
        with self._lock.acquire(timeout=10.0):
            entries = self.list()
            entries[pci_bdf] = {"nativeDriver": native_driver or ""}
            self._write(entries)

    def remove(self, pci_bdf: str) -> None:
        with self._lock.acquire(timeout=10.0):
            entries = self.list()
            if entries.pop(pci_bdf, None) is not None:
                self._write(entries)

    def native_driver(self, pci_bdf: str) -> str | None:
        return self.list().get(pci_bdf, {}).get("nativeDriver") or None


class VfioPciManager:
    def __init__(self, sys_root: str = "/sys", dev_root: str = "/dev",
                 registry: VfioRegistry | None = None):
        self._sys = sys_root
        self._dev = dev_root
        self.registry = registry

    # -- sysfs paths ------------------------------------------------------------

    def _device_dir(self, pci_bdf: str) -> str:
        return os.path.join(self._sys, "bus", "pci", "devices", pci_bdf)

    def _driver_override(self, pci_bdf: str) -> str:
        return os.path.join(self._device_dir(pci_bdf), "driver_override")

    def _current_driver(self, pci_bdf: str) -> str | None:
        link = os.path.join(self._device_dir(pci_bdf), "driver")
        try:
            return os.path.basename(os.readlink(link))
        except OSError:
            return None

    def iommu_group(self, pci_bdf: str) -> int:
        link = os.path.join(self._device_dir(pci_bdf), "iommu_group")
        try:
            return int(os.path.basename(os.readlink(link)))
        except (OSError, ValueError):
            return -1

    # -- bind/unbind --------------------------------------------------------------

    def _write(self, path: str, value: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            f.write(value)

    def _unbind(self, pci_bdf: str, driver: str) -> None:
        unbind = os.path.join(self._sys, "bus", "pci", "drivers", driver,
                              "unbind")
        try:
            self._write(unbind, pci_bdf)
        except OSError as e:
            logger.warning("unbind %s from %s: %s", pci_bdf, driver, e)

    def _bind(self, pci_bdf: str, driver: str) -> None:
        bind = os.path.join(self._sys, "bus", "pci", "drivers", driver,
                            "bind")
        self._write(bind, pci_bdf)

    def configure(self, pci_bdf: str, cfg: PassthroughConfig) -> ContainerEdits:
        """Rebind the function to vfio-pci and emit the CDI edits
        (Configure analog, vfio-device.go:145)."""
        group_pre = self.iommu_group(pci_bdf)
        if group_pre < 0:
            raise RuntimeError(
                f"device {pci_bdf} has no iommu group (IOMMU disabled?); "
                "refusing passthrough"
            )
        current = self._current_driver(pci_bdf)
        if current != VFIO_DRIVER:
            # Record the rebind (and the driver to restore) BEFORE
            # touching sysfs: a crash mid-rebind must be reconcilable.
            if self.registry is not None:
                self.registry.add(pci_bdf, current)
            if current:
                self._unbind(pci_bdf, current)
            self._write(self._driver_override(pci_bdf), VFIO_DRIVER)
            self._bind(pci_bdf, VFIO_DRIVER)
        group = self.iommu_group(pci_bdf)
        if cfg.iommu_mode == "iommufd":
            dev_node = os.path.join(self._dev, "vfio", "devices",
                                    f"vfio{group}")
        else:
            dev_node = os.path.join(self._dev, "vfio", str(group))
        return ContainerEdits(
            env=[f"TPU_VFIO_GROUP={group}",
                 f"TPU_VFIO_MODE={cfg.iommu_mode}"],
            device_nodes=[os.path.join(self._dev, "vfio", "vfio"), dev_node],
        )

    def unconfigure(self, pci_bdf: str) -> None:
        """Return the function to its recorded native driver
        (Unconfigure :189)."""
        native = None
        if self.registry is not None:
            native = self.registry.native_driver(pci_bdf)
        native = native or NATIVE_DRIVER
        if self._current_driver(pci_bdf) == VFIO_DRIVER:
            self._unbind(pci_bdf, VFIO_DRIVER)
        try:
            self._write(self._driver_override(pci_bdf), "\n")
        except OSError:
            pass
        try:
            self._bind(pci_bdf, native)
        except OSError as e:
            logger.warning("rebind %s to %s: %s", pci_bdf, native, e)
        if self.registry is not None:
            self.registry.remove(pci_bdf)
