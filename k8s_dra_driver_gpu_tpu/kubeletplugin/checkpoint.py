"""Versioned, checksummed, crash-safe claim checkpointing.

Reference: cmd/gpu-kubelet-plugin/{checkpoint.go,checkpointv.go} --
versioned on-disk JSON with V1+V2 dual checksums for seamless up/downgrade
(checkpoint.go:26-66), omitempty-hardened device marshalling (issue 1080,
checkpointv.go:29-57), claim-state enum (:59-66), NodeBootID invalidation
on reboot (:74-81), corruption diagnosis via on-disk vs re-marshaled diff
(device_state.go:618-646), and a flock guarding read-modify-write across
processes (device_state.go:648-676).

Schema versions:
  v1: {claims: {uid: {state, devices}}}                 (legacy carry)
  v2: v1 + nodeBootID + per-claim namespace/name for API-server
      validation by the stale-claim GC.
"""

from __future__ import annotations

import difflib
import json
import logging
import os
import threading
import time
import zlib
from dataclasses import dataclass, field
from enum import Enum

from ..pkg import bootid, faults
from ..pkg.analysis.statemachine import TransitionPolicy
from ..pkg.flock import Flock, FlockReentrantError
from ..pkg.fsutil import stat_signature

logger = logging.getLogger(__name__)

LATEST_VERSION = "v2"


class ClaimState(str, Enum):
    PREPARE_STARTED = "PrepareStarted"
    PREPARE_COMPLETED = "PrepareCompleted"


@dataclass
class CheckpointedDevice:
    """One prepared device record. All fields serialize omitempty-style:
    absent keys decode to defaults (the reference hardened this after
    issue 1080 -- a schema change that dropped empty fields corrupted
    checksums across up/downgrade)."""

    canonical_name: str = ""
    kind: str = ""  # DeviceKind value
    cdi_device_ids: list[str] = field(default_factory=list)
    # Dynamic sub-slice live identity (None for static devices).
    live: dict | None = None

    def to_dict(self) -> dict:
        d: dict = {}
        if self.canonical_name:
            d["canonicalName"] = self.canonical_name
        if self.kind:
            d["kind"] = self.kind
        if self.cdi_device_ids:
            d["cdiDeviceIDs"] = self.cdi_device_ids
        if self.live is not None:
            d["live"] = self.live
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "CheckpointedDevice":
        return cls(
            canonical_name=d.get("canonicalName", ""),
            kind=d.get("kind", ""),
            cdi_device_ids=list(d.get("cdiDeviceIDs", [])),
            live=d.get("live"),
        )


@dataclass
class CheckpointedClaim:
    uid: str = ""
    namespace: str = ""
    name: str = ""
    state: str = ClaimState.PREPARE_STARTED.value
    devices: list[CheckpointedDevice] = field(default_factory=list)
    # NOTE: the prepare-reservation pid-lease deliberately does NOT
    # live in this record: adding fields to the v2 payload would break
    # cross-version checksum verification during upgrade handover (the
    # issue-1080 class). It is a sidecar file -- see
    # device_state._ReservationLeases.

    def to_dict(self) -> dict:
        d: dict = {"uid": self.uid, "state": self.state}
        if self.namespace:
            d["namespace"] = self.namespace
        if self.name:
            d["name"] = self.name
        if self.devices:
            d["devices"] = [x.to_dict() for x in self.devices]
        return d

    def to_dict_v1(self) -> dict:
        # v1 lacked namespace/name.
        d: dict = {"uid": self.uid, "state": self.state}
        if self.devices:
            d["devices"] = [x.to_dict() for x in self.devices]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "CheckpointedClaim":
        return cls(
            uid=d.get("uid", ""),
            namespace=d.get("namespace", ""),
            name=d.get("name", ""),
            state=d.get("state", ClaimState.PREPARE_STARTED.value),
            devices=[
                CheckpointedDevice.from_dict(x) for x in d.get("devices", [])
            ],
        )


def _checksum(payload: dict) -> int:
    """Deterministic checksum over the canonical JSON encoding."""
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return zlib.crc32(blob.encode())


@dataclass
class Checkpoint:
    """The in-memory checkpoint document."""

    node_boot_id: str = ""
    claims: dict[str, CheckpointedClaim] = field(default_factory=dict)

    # -- serialization --------------------------------------------------------

    def _payload_v2(self) -> dict:
        return {
            "nodeBootID": self.node_boot_id,
            "claims": {uid: c.to_dict() for uid, c in self.claims.items()},
        }

    def _payload_v1(self) -> dict:
        # v1 lacked boot-id and namespace/name.
        return {
            "claims": {uid: c.to_dict_v1() for uid, c in self.claims.items()}
        }

    def to_dict(self) -> dict:
        """Dual-checksum envelope: a vN reader verifies checksum[vN] over
        its own projection of the payload, so up/downgrades never see a
        'corrupt' file (checkpoint.go:53-66)."""
        return {
            "version": LATEST_VERSION,
            "data": self._payload_v2(),
            "checksums": {
                "v1": _checksum(self._payload_v1()),
                "v2": _checksum(self._payload_v2()),
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Checkpoint":
        version = d.get("version", "v1")
        data = d.get("data", {})
        cp = cls(
            node_boot_id=data.get("nodeBootID", ""),
            claims={
                uid: CheckpointedClaim.from_dict(c)
                for uid, c in data.get("claims", {}).items()
            },
        )
        checks = d.get("checksums", {})
        want = checks.get("v2" if version == "v2" else "v1")
        if want is not None:
            have = _checksum(
                cp._payload_v2() if version == "v2" else cp._payload_v1()
            )
            if have != want:
                raise CheckpointCorruptError(_diagnose(d, cp, version))
        return cp


class CheckpointCorruptError(RuntimeError):
    pass


def _diagnose(on_disk: dict, cp: Checkpoint, version: str) -> str:
    """Unified diff of on-disk vs re-marshaled payload
    (device_state.go:618-646)."""
    a = json.dumps(on_disk.get("data", {}), sort_keys=True, indent=1)
    b = json.dumps(
        cp._payload_v2() if version == "v2" else cp._payload_v1(),
        sort_keys=True,
        indent=1,
    )
    diff = "\n".join(
        difflib.unified_diff(
            a.splitlines(), b.splitlines(), "on-disk", "re-marshaled", lineterm=""
        )
    )
    return f"checkpoint checksum mismatch ({version}); diff:\n{diff}"


class _Commit:
    """One enqueued checkpoint mutation: the flusher that writes the
    batch containing it sets ``err`` (None on success) and ``done``."""

    __slots__ = ("fn", "dirty", "done", "err")

    def __init__(self, fn, dirty):
        self.fn = fn
        self.dirty = dirty  # uids whose fragments fn touches; None = all
        self.done = threading.Event()
        self.err: BaseException | None = None


class CheckpointManager:
    """Flock-guarded, group-committed writer of checkpoint.json.

    On startup: if the recorded boot ID differs from the node's current
    one, the checkpoint is invalidated wholesale (a reboot destroyed all
    device state; checkpointv.go:74-81, device_state.go:190-215).

    Concurrency design (the claim-prepare hot path):

    - **Stat-validated read cache.** The parsed Checkpoint is kept in
      memory; get()/update only re-read the file when its
      (mtime_ns, size) signature changed -- i.e. when ANOTHER process
      wrote it (upgrade handover). Same-process callers pay a stat, not
      a parse.
    - **Dirty-tracked claim fragments.** The canonical JSON encoding of
      each claim (the input to both the v1 and v2 checksums) is cached
      per uid and invalidated only for claims a mutation touched, so a
      single-claim update re-encodes one claim, not all N.
      ``update_claim`` is the precise API; the legacy ``update(fn)``
      conservatively marks everything dirty.
    - **Group commit.** Mutations enqueue; one flusher thread at a time
      drains the whole queue into ONE read-apply-write-fdatasync cycle
      under the flock, then wakes every committer whose mutation the
      batch covered. Concurrent committers therefore share a single
      fsync instead of serializing N of them. A committer returns only
      after its mutation is durable, preserving the two-phase-prepare
      invariant (PrepareStarted on disk before any device mutation).
    """

    FILENAME = "checkpoint.json"

    def __init__(self, root: str, boot_id: str | None = None,
                 transition_policy: TransitionPolicy | None = None):
        os.makedirs(root, exist_ok=True)
        self._path = os.path.join(root, self.FILENAME)
        self._lock = Flock(os.path.join(root, "checkpoint.lock"))
        # Checkpoint state-machine runtime validator
        # (pkg/analysis/statemachine.py): every committed mutation's
        # per-claim state change must be a legal lifecycle transition,
        # or the batch fails and the cache is poisoned. None = legacy
        # unvalidated (tests exercising corruption paths).
        self.transition_policy = transition_policy
        self._boot_id = (
            boot_id if boot_id is not None else bootid.read_boot_id()
        )
        # In-memory mirror + fragment caches; all guarded by self._lock
        # (its internal thread mutex serializes same-process access).
        self._cp: Checkpoint | None = None
        self._sig: tuple[int, int, int] | None = None
        self._frags_v1: dict[str, str] = {}
        self._frags_v2: dict[str, str] = {}
        # Group-commit state, guarded by self._cond. _flusher_thread is
        # the flusher's thread ident: only the flusher itself can ever
        # match its own ident, so the unlocked read in _submit is
        # race-free for the re-entrancy check (same argument as
        # Flock._owner).
        self._cond = threading.Condition()
        self._pending: list[_Commit] = []
        self._flusher_active = False
        self._flusher_thread: int | None = None

        self.invalidated_on_boot = False
        with self._lock.acquire(timeout=10.0):
            cp = self._read_locked()
            if cp.node_boot_id and self._boot_id and cp.node_boot_id != self._boot_id:
                logger.warning(
                    "node boot ID changed (%s -> %s): invalidating checkpoint "
                    "with %d claim(s)",
                    cp.node_boot_id, self._boot_id, len(cp.claims),
                )
                cp = Checkpoint(node_boot_id=self._boot_id)
                self._invalidate_frags(None)
                self._write_locked(cp)
                self.invalidated_on_boot = True
            elif not cp.node_boot_id:
                cp.node_boot_id = self._boot_id
                self._write_locked(cp)

    @property
    def path(self) -> str:
        return self._path

    # -- cached read / fragment-assembled write (call under self._lock) -------

    def _stat_sig(self) -> tuple[int, int, int] | None:
        return stat_signature(self._path)

    def _read_locked(self) -> Checkpoint:
        sig = self._stat_sig()
        if self._cp is not None and sig is not None and sig == self._sig:
            return self._cp
        if sig is None:
            cp = Checkpoint(node_boot_id="")
        else:
            with open(self._path, "r", encoding="utf-8") as f:
                cp = Checkpoint.from_dict(json.load(f))
        # Cache only after a successful parse; corruption propagates and
        # leaves the cache untouched so the next read retries the file.
        self._cp = cp
        self._sig = sig
        self._invalidate_frags(None)
        return cp

    def _invalidate_frags(self, dirty_uids) -> None:
        if dirty_uids is None:
            self._frags_v1.clear()
            self._frags_v2.clear()
        else:
            for uid in dirty_uids:
                self._frags_v1.pop(uid, None)
                self._frags_v2.pop(uid, None)

    def _payload_str(self, cp: Checkpoint, version: str) -> str:
        """Canonical JSON (sort_keys + compact separators) assembled
        from cached per-claim fragments -- byte-identical to
        ``json.dumps(payload, sort_keys=True, separators=(",", ":"))``
        over the corresponding ``_payload_vN()`` dict, which is what
        the checksum verifier re-marshals on read."""
        frags = self._frags_v2 if version == "v2" else self._frags_v1
        parts = []
        for uid in sorted(cp.claims):
            frag = frags.get(uid)
            if frag is None:
                claim = cp.claims[uid]
                d = claim.to_dict() if version == "v2" else claim.to_dict_v1()
                frag = json.dumps(d, sort_keys=True, separators=(",", ":"))
                frags[uid] = frag
            parts.append(f"{json.dumps(uid)}:{frag}")
        claims = "{" + ",".join(parts) + "}"
        if version == "v2":
            return ('{"claims":' + claims + ',"nodeBootID":'
                    + json.dumps(cp.node_boot_id) + "}")
        return '{"claims":' + claims + "}"

    def _write_locked(self, cp: Checkpoint) -> None:
        cp.node_boot_id = cp.node_boot_id or self._boot_id
        # Stale fragments for uids no longer present would leak; drop them.
        for uid in set(self._frags_v2) - set(cp.claims):
            self._frags_v1.pop(uid, None)
            self._frags_v2.pop(uid, None)
        v1 = self._payload_str(cp, "v1")
        v2 = self._payload_str(cp, "v2")
        doc = (
            '{"version":"' + LATEST_VERSION + '","data":' + v2
            + ',"checksums":{"v1":' + str(zlib.crc32(v1.encode()))
            + ',"v2":' + str(zlib.crc32(v2.encode())) + "}}"
        )
        # Fault seams bracketing durability: "ckpt.write" fails the
        # whole write; "ckpt.fsync" fires AFTER the tmp file holds the
        # bytes but BEFORE they are durable/renamed -- the
        # crash-between-write-and-fsync window the recovery sweep must
        # tolerate (tests/test_prepare_concurrency.py).
        faults.fault_point("ckpt.write", error=lambda m: OSError(m))
        tmp = self._path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(doc)
            f.flush()
            faults.fault_point("ckpt.fsync", error=lambda m: OSError(m))
            # fdatasync: the data must be durable before the rename; the
            # tmp file's metadata (mtime) need not be -- saves one
            # journal commit per write on the 2x-per-Prepare hot path.
            os.fdatasync(f.fileno())
        os.replace(tmp, self._path)
        self._cp = cp
        self._sig = self._stat_sig()

    # -- public API -----------------------------------------------------------

    def get(self) -> Checkpoint:
        """A read snapshot. The claims mapping is a fresh dict; the claim
        objects are shared with the cache -- treat them as read-only."""
        with self._lock.acquire(timeout=10.0):
            cp = self._read_locked()
            return Checkpoint(node_boot_id=cp.node_boot_id,
                              claims=dict(cp.claims))

    def update(self, fn) -> None:
        """Atomic read-modify-write: fn(checkpoint) mutates in place.
        Arbitrary mutation -> every claim fragment is marked dirty; hot
        paths should prefer update_claim()."""
        self._submit(fn, None)

    def update_claim(self, uid: str, claim: CheckpointedClaim | None,
                     timer=None) -> None:
        """Upsert (or, with None, remove) ONE claim record. Re-encodes
        only that claim; the wait for the (possibly shared) fsync is
        recorded as the timer's ``ckpt_fsync_wait`` segment."""
        def fn(cp: Checkpoint) -> None:
            if claim is None:
                cp.claims.pop(uid, None)
            else:
                cp.claims[uid] = claim

        self._submit(fn, {uid}, timer=timer)

    # -- group commit ---------------------------------------------------------

    def _submit(self, fn, dirty_uids, timer=None) -> None:
        # A mutation fn calling back into update()/update_claim() would
        # park the flusher on its own queue: _flusher_active stays set,
        # so the nested commit's wait loop can never be satisfied -- an
        # unbounded 1s-poll stall that reads like fsync trouble. Fail
        # fast and name the bug, exactly like Flock re-entrancy
        # (surfaced by the interleaving explorer work, ISSUE 3).
        if self._flusher_thread == threading.get_ident():
            raise FlockReentrantError(
                f"checkpoint commit on {self._path} re-entered from "
                "inside its own mutation fn; commit fns must not call "
                "update()/update_claim()/get()"
            )
        t0 = time.monotonic()
        commit = _Commit(fn, dirty_uids)
        try:
            with self._cond:
                self._pending.append(commit)
            while True:
                with self._cond:
                    if commit.done.is_set():
                        break
                    if self._flusher_active or not self._pending:
                        # Another thread's flush covers us (or already
                        # took us into its batch); it notifies when the
                        # outcome of OUR batch is known.
                        self._cond.wait(timeout=1.0)
                        continue
                    self._flusher_active = True
                    self._flusher_thread = threading.get_ident()
                    batch = self._pending
                    self._pending = []
                self._flush(batch)
            if commit.err is not None:
                raise RuntimeError(
                    "checkpoint group commit failed"
                ) from commit.err
        finally:
            if timer is not None:
                timer.segments["ckpt_fsync_wait"] = timer.segments.get(
                    "ckpt_fsync_wait", 0.0) + (time.monotonic() - t0)

    def _apply_one_locked(self, cp: Checkpoint, fn, dirty_uids) -> None:
        """Apply one mutation to the in-memory checkpoint (under the
        flock) and validate its claim-state transitions against the
        declared policy. Shared by the group-commit flusher and the
        interleaving explorer's deterministic commit path."""
        policy = self.transition_policy
        old_states = (
            {uid: c.state for uid, c in cp.claims.items()}
            if policy is not None else None
        )
        fn(cp)
        if policy is not None:
            policy.validate_states(
                old_states,
                {uid: c.state for uid, c in cp.claims.items()},
                scope=dirty_uids,
            )
        self._invalidate_frags(dirty_uids)

    def _flush(self, batch: list["_Commit"]) -> None:
        err: BaseException | None = None
        try:
            with self._lock.acquire(timeout=10.0):
                try:
                    cp = self._read_locked()
                    for commit in batch:
                        self._apply_one_locked(cp, commit.fn, commit.dirty)
                    self._write_locked(cp)
                except BaseException:
                    # The cached Checkpoint may hold the batch's partial
                    # (never-persisted) mutations: poison it so the next
                    # reader re-parses the durable file.
                    self._cp = None
                    self._sig = None
                    self._invalidate_frags(None)
                    raise
        except BaseException as e:  # noqa: BLE001 - propagated to waiters
            err = e
        with self._cond:
            self._flusher_active = False
            self._flusher_thread = None
            # Per-commit outcome: only the commits whose mutations were
            # in THIS failed batch see the error; a commit that already
            # flushed durably can never be failed retroactively by a
            # later batch's write error.
            for commit in batch:
                commit.err = err
                commit.done.set()
            self._cond.notify_all()
        # No raise here: every committer in the batch (this thread
        # included) reports through its own commit.err in _submit.
