"""Versioned, checksummed, crash-safe claim checkpointing.

Reference: cmd/gpu-kubelet-plugin/{checkpoint.go,checkpointv.go} --
versioned on-disk JSON with V1+V2 dual checksums for seamless up/downgrade
(checkpoint.go:26-66), omitempty-hardened device marshalling (issue 1080,
checkpointv.go:29-57), claim-state enum (:59-66), NodeBootID invalidation
on reboot (:74-81), corruption diagnosis via on-disk vs re-marshaled diff
(device_state.go:618-646), and a flock guarding read-modify-write across
processes (device_state.go:648-676).

Schema versions:
  v1: {claims: {uid: {state, devices}}}                 (legacy carry)
  v2: v1 + nodeBootID + per-claim namespace/name for API-server
      validation by the stale-claim GC.
"""

from __future__ import annotations

import difflib
import json
import logging
import os
import zlib
from dataclasses import dataclass, field
from enum import Enum

from ..pkg import bootid
from ..pkg.flock import Flock

logger = logging.getLogger(__name__)

LATEST_VERSION = "v2"


class ClaimState(str, Enum):
    PREPARE_STARTED = "PrepareStarted"
    PREPARE_COMPLETED = "PrepareCompleted"


@dataclass
class CheckpointedDevice:
    """One prepared device record. All fields serialize omitempty-style:
    absent keys decode to defaults (the reference hardened this after
    issue 1080 -- a schema change that dropped empty fields corrupted
    checksums across up/downgrade)."""

    canonical_name: str = ""
    kind: str = ""  # DeviceKind value
    cdi_device_ids: list[str] = field(default_factory=list)
    # Dynamic sub-slice live identity (None for static devices).
    live: dict | None = None

    def to_dict(self) -> dict:
        d: dict = {}
        if self.canonical_name:
            d["canonicalName"] = self.canonical_name
        if self.kind:
            d["kind"] = self.kind
        if self.cdi_device_ids:
            d["cdiDeviceIDs"] = self.cdi_device_ids
        if self.live is not None:
            d["live"] = self.live
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "CheckpointedDevice":
        return cls(
            canonical_name=d.get("canonicalName", ""),
            kind=d.get("kind", ""),
            cdi_device_ids=list(d.get("cdiDeviceIDs", [])),
            live=d.get("live"),
        )


@dataclass
class CheckpointedClaim:
    uid: str = ""
    namespace: str = ""
    name: str = ""
    state: str = ClaimState.PREPARE_STARTED.value
    devices: list[CheckpointedDevice] = field(default_factory=list)

    def to_dict(self) -> dict:
        d: dict = {"uid": self.uid, "state": self.state}
        if self.namespace:
            d["namespace"] = self.namespace
        if self.name:
            d["name"] = self.name
        if self.devices:
            d["devices"] = [x.to_dict() for x in self.devices]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "CheckpointedClaim":
        return cls(
            uid=d.get("uid", ""),
            namespace=d.get("namespace", ""),
            name=d.get("name", ""),
            state=d.get("state", ClaimState.PREPARE_STARTED.value),
            devices=[
                CheckpointedDevice.from_dict(x) for x in d.get("devices", [])
            ],
        )


def _checksum(payload: dict) -> int:
    """Deterministic checksum over the canonical JSON encoding."""
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return zlib.crc32(blob.encode())


@dataclass
class Checkpoint:
    """The in-memory checkpoint document."""

    node_boot_id: str = ""
    claims: dict[str, CheckpointedClaim] = field(default_factory=dict)

    # -- serialization --------------------------------------------------------

    def _payload_v2(self) -> dict:
        return {
            "nodeBootID": self.node_boot_id,
            "claims": {uid: c.to_dict() for uid, c in self.claims.items()},
        }

    def _payload_v1(self) -> dict:
        # v1 lacked boot-id and namespace/name.
        return {
            "claims": {
                uid: {
                    "uid": c.uid,
                    "state": c.state,
                    **(
                        {"devices": [x.to_dict() for x in c.devices]}
                        if c.devices
                        else {}
                    ),
                }
                for uid, c in self.claims.items()
            }
        }

    def to_dict(self) -> dict:
        """Dual-checksum envelope: a vN reader verifies checksum[vN] over
        its own projection of the payload, so up/downgrades never see a
        'corrupt' file (checkpoint.go:53-66)."""
        return {
            "version": LATEST_VERSION,
            "data": self._payload_v2(),
            "checksums": {
                "v1": _checksum(self._payload_v1()),
                "v2": _checksum(self._payload_v2()),
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Checkpoint":
        version = d.get("version", "v1")
        data = d.get("data", {})
        cp = cls(
            node_boot_id=data.get("nodeBootID", ""),
            claims={
                uid: CheckpointedClaim.from_dict(c)
                for uid, c in data.get("claims", {}).items()
            },
        )
        checks = d.get("checksums", {})
        want = checks.get("v2" if version == "v2" else "v1")
        if want is not None:
            have = _checksum(
                cp._payload_v2() if version == "v2" else cp._payload_v1()
            )
            if have != want:
                raise CheckpointCorruptError(_diagnose(d, cp, version))
        return cp


class CheckpointCorruptError(RuntimeError):
    pass


def _diagnose(on_disk: dict, cp: Checkpoint, version: str) -> str:
    """Unified diff of on-disk vs re-marshaled payload
    (device_state.go:618-646)."""
    a = json.dumps(on_disk.get("data", {}), sort_keys=True, indent=1)
    b = json.dumps(
        cp._payload_v2() if version == "v2" else cp._payload_v1(),
        sort_keys=True,
        indent=1,
    )
    diff = "\n".join(
        difflib.unified_diff(
            a.splitlines(), b.splitlines(), "on-disk", "re-marshaled", lineterm=""
        )
    )
    return f"checkpoint checksum mismatch ({version}); diff:\n{diff}"


class CheckpointManager:
    """Flock-guarded read-modify-write of checkpoint.json.

    On startup: if the recorded boot ID differs from the node's current
    one, the checkpoint is invalidated wholesale (a reboot destroyed all
    device state; checkpointv.go:74-81, device_state.go:190-215).
    """

    FILENAME = "checkpoint.json"

    def __init__(self, root: str, boot_id: str | None = None):
        os.makedirs(root, exist_ok=True)
        self._path = os.path.join(root, self.FILENAME)
        self._lock = Flock(os.path.join(root, "checkpoint.lock"))
        self._boot_id = (
            boot_id if boot_id is not None else bootid.read_boot_id()
        )
        self.invalidated_on_boot = False
        with self._lock.acquire(timeout=10.0):
            cp = self._read()
            if cp.node_boot_id and self._boot_id and cp.node_boot_id != self._boot_id:
                logger.warning(
                    "node boot ID changed (%s -> %s): invalidating checkpoint "
                    "with %d claim(s)",
                    cp.node_boot_id, self._boot_id, len(cp.claims),
                )
                cp = Checkpoint(node_boot_id=self._boot_id)
                self._write(cp)
                self.invalidated_on_boot = True
            elif not cp.node_boot_id:
                cp.node_boot_id = self._boot_id
                self._write(cp)

    @property
    def path(self) -> str:
        return self._path

    def _read(self) -> Checkpoint:
        if not os.path.exists(self._path):
            return Checkpoint(node_boot_id="")
        with open(self._path, "r", encoding="utf-8") as f:
            return Checkpoint.from_dict(json.load(f))

    def _write(self, cp: Checkpoint) -> None:
        cp.node_boot_id = cp.node_boot_id or self._boot_id
        tmp = self._path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(cp.to_dict(), f, indent=1)
            f.flush()
            # fdatasync: the data must be durable before the rename; the
            # tmp file's metadata (mtime) need not be -- saves one
            # journal commit per write on the 2x-per-Prepare hot path.
            os.fdatasync(f.fileno())
        os.replace(tmp, self._path)

    def get(self) -> Checkpoint:
        with self._lock.acquire(timeout=10.0):
            return self._read()

    def update(self, fn) -> Checkpoint:
        """Atomic read-modify-write: fn(checkpoint) mutates in place."""
        with self._lock.acquire(timeout=10.0):
            cp = self._read()
            fn(cp)
            self._write(cp)
            return cp
