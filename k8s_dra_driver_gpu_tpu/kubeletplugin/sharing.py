"""Sharing managers: time-slicing and multi-tenant co-tenancy.

Reference: cmd/gpu-kubelet-plugin/sharing.go -- TimeSlicingManager sets
the per-GPU compute timeslice via nvidia-smi (:135); MpsManager runs a
per-claim MPS control-daemon Deployment and points workloads at its pipe
dir via CDI edits (:214-379).

TPU translation: there is no per-chip preemption ioctl exposed by libtpu;
temporal sharing on TPU is cooperative multi-process scheduling, which
the runtime activates from environment + a shared coordination directory.
So:
- TimeSlicingManager records the chip's policy in a node-local policy
  file (the admin surface an actual scheduler daemon consumes) and emits
  the env contract for workloads.
- MultiTenancyManager provisions a per-claim tenancy directory (shm-like
  rendezvous the co-tenant processes share, the MPS-pipe-dir analog),
  enforces max-client/HBM limits via env, and cleans up on unprepare.
"""

from __future__ import annotations

import json
import os
import shutil

from ..api.configs import MultiTenancyConfig, TimeSlicingConfig
from .cdi import ContainerEdits

# Interval name -> microseconds budget per tenant timeslice.
_INTERVALS_US = {
    "Default": 5000,
    "Short": 1000,
    "Medium": 5000,
    "Long": 20000,
}


class TimeSlicingManager:
    """Per-chip temporal-sharing policy (TimeSlicingManager analog).

    Policies are holder-counted: a chip can be shared by several claims
    (disjoint core-level carve-outs), so the policy file persists until
    the last holding claim releases it.
    """

    def __init__(self, policy_root: str):
        self._root = os.path.join(policy_root, "timeslice")
        os.makedirs(self._root, exist_ok=True)

    def _path(self, chip_index: int) -> str:
        return os.path.join(self._root, f"chip-{chip_index}.json")

    def _load(self, chip_index: int) -> dict | None:
        try:
            with open(self._path(chip_index), encoding="utf-8") as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def set_time_slice(
        self, claim_uid: str, chip_indices: list[int], cfg: TimeSlicingConfig
    ) -> ContainerEdits:
        interval_us = _INTERVALS_US[cfg.interval]
        for idx in chip_indices:
            doc = self._load(idx) or {"holders": {}}
            doc["interval"] = cfg.interval  # last setter wins
            doc["intervalUs"] = interval_us
            doc.setdefault("holders", {})[claim_uid] = cfg.interval
            with open(self._path(idx), "w", encoding="utf-8") as f:
                json.dump(doc, f)
        return ContainerEdits(
            env=[
                f"TPU_TIMESLICE_INTERVAL_US={interval_us}",
                "TPU_PROCESS_SHARING=cooperative",
            ]
        )

    def release(self, claim_uid: str, chip_indices: list[int]) -> None:
        """Drop this claim's hold; the policy file disappears only when no
        other claim still shares the chip."""
        for idx in chip_indices:
            doc = self._load(idx)
            if doc is None:
                continue
            doc.get("holders", {}).pop(claim_uid, None)
            if doc.get("holders"):
                with open(self._path(idx), "w", encoding="utf-8") as f:
                    json.dump(doc, f)
            else:
                try:
                    os.unlink(self._path(idx))
                except FileNotFoundError:
                    pass

    def current(self, chip_index: int) -> dict | None:
        return self._load(chip_index)


class MultiTenancyManager:
    """Per-claim co-tenancy rendezvous (MpsManager/MpsControlDaemon
    analog, sharing.go:214-379)."""

    def __init__(self, tenancy_root: str):
        self._root = os.path.join(tenancy_root, "tenancy")
        os.makedirs(self._root, exist_ok=True)

    def _dir(self, claim_uid: str, request: str | None = None) -> str:
        d = os.path.join(self._root, claim_uid)
        return os.path.join(d, request) if request else d

    def start(
        self,
        claim_uid: str,
        request: str,
        chip_indices: list[int],
        cfg: MultiTenancyConfig,
        device_names: list[str],
    ) -> ContainerEdits:
        """Provision the per-request tenancy dir + emit workload env/mount
        edits. One call per request group covers all its devices."""
        d = self._dir(claim_uid, request)
        os.makedirs(d, exist_ok=True)
        manifest = {
            "chips": chip_indices,
            "maxClients": cfg.max_clients,
            "hbmLimits": {
                name: cfg.hbm_limit_bytes_for(name) for name in device_names
            },
        }
        with open(os.path.join(d, "tenancy.json"), "w", encoding="utf-8") as f:
            json.dump(manifest, f)
        env = [
            "TPU_MULTI_TENANT=1",
            f"TPU_TENANCY_DIR=/var/run/tpu-tenancy/{claim_uid}/{request}",
        ]
        if cfg.max_clients is not None:
            env.append(f"TPU_MAX_TENANTS={cfg.max_clients}")
        limits = [
            str(v) for v in manifest["hbmLimits"].values() if v is not None
        ]
        if limits:
            # Uniform per-group limit contract; per-device granularity
            # rides the manifest mount.
            env.append(f"TPU_HBM_LIMIT_BYTES={min(map(int, limits))}")
        return ContainerEdits(
            env=env,
            # Writable: co-tenant processes create rendezvous files here.
            mounts=[(d, f"/var/run/tpu-tenancy/{claim_uid}/{request}", False)],
        )

    def stop(self, claim_uid: str) -> None:
        shutil.rmtree(self._dir(claim_uid), ignore_errors=True)

    def active(self, claim_uid: str) -> bool:
        return os.path.isdir(self._dir(claim_uid))
