"""Sharing managers: time-slicing and multi-tenant co-tenancy.

Reference: cmd/gpu-kubelet-plugin/sharing.go -- TimeSlicingManager sets
the per-GPU compute timeslice via nvidia-smi (:135); MpsManager runs a
per-claim MPS control-daemon Deployment and points workloads at its pipe
dir via CDI edits (:214-379).

TPU translation: there is no per-chip preemption ioctl exposed by libtpu;
temporal sharing on TPU is cooperative multi-process scheduling, which
the runtime activates from environment + a shared coordination directory.
So:
- TimeSlicingManager records the chip's policy in a node-local policy
  file (the admin surface an actual scheduler daemon consumes) and emits
  the env contract for workloads.
- MultiTenancyManager provisions a per-claim tenancy directory (shm-like
  rendezvous the co-tenant processes share, the MPS-pipe-dir analog),
  enforces max-client/HBM limits via env, and cleans up on unprepare.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import sys
import threading
import time

from ..api.configs import MultiTenancyConfig, TimeSlicingConfig
from ..pkg.flock import Flock
from ..pkg.fsutil import write_json_atomic
from .cdi import ContainerEdits

logger = logging.getLogger(__name__)

# Interval name -> microseconds budget per tenant timeslice.
_INTERVALS_US = {
    "Default": 5000,
    "Short": 1000,
    "Medium": 5000,
    "Long": 20000,
}


class TimeSlicingManager:
    """Per-chip temporal-sharing policy (TimeSlicingManager analog).

    Policies are holder-counted: a chip can be shared by several claims
    (disjoint core-level carve-outs), so the policy file persists until
    the last holding claim releases it.
    """

    def __init__(self, policy_root: str):
        self._root = os.path.join(policy_root, "timeslice")
        os.makedirs(self._root, exist_ok=True)
        # Holder-file read-modify-write guard: with sharded prepares,
        # two claims sharing a chip via disjoint core-level carve-outs
        # still serialize on the SAME shard in-process, but another
        # plugin process (upgrade handover) does not -- the flock covers
        # both.
        self._lock = Flock(os.path.join(policy_root, "timeslice.lock"))

    def _path(self, chip_index: int) -> str:
        return os.path.join(self._root, f"chip-{chip_index}.json")

    def _load(self, chip_index: int) -> dict | None:
        try:
            with open(self._path(chip_index), encoding="utf-8") as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def set_time_slice(
        self, claim_uid: str, chip_indices: list[int], cfg: TimeSlicingConfig
    ) -> ContainerEdits:
        interval_us = _INTERVALS_US[cfg.interval]
        with self._lock.acquire(timeout=10.0):
            for idx in chip_indices:
                doc = self._load(idx) or {"holders": {}}
                doc["interval"] = cfg.interval  # last setter wins
                doc["intervalUs"] = interval_us
                doc.setdefault("holders", {})[claim_uid] = cfg.interval
                with open(self._path(idx), "w", encoding="utf-8") as f:
                    json.dump(doc, f)
        return ContainerEdits(
            env=[
                f"TPU_TIMESLICE_INTERVAL_US={interval_us}",
                "TPU_PROCESS_SHARING=cooperative",
            ]
        )

    def release(self, claim_uid: str, chip_indices: list[int]) -> None:
        """Drop this claim's hold; the policy file disappears only when no
        other claim still shares the chip."""
        with self._lock.acquire(timeout=10.0):
            for idx in chip_indices:
                doc = self._load(idx)
                if doc is None:
                    continue
                doc.get("holders", {}).pop(claim_uid, None)
                if doc.get("holders"):
                    with open(self._path(idx), "w", encoding="utf-8") as f:
                        json.dump(doc, f)
                else:
                    try:
                        os.unlink(self._path(idx))
                    except FileNotFoundError:
                        pass

    def current(self, chip_index: int) -> dict | None:
        return self._load(chip_index)


class TenancyAgentError(RuntimeError):
    """The per-claim tenancy agent failed to become ready."""


class MultiTenancyManager:
    """Per-claim co-tenancy enforcement (MpsManager/MpsControlDaemon
    analog, sharing.go:214-379).

    With ``spawn_agents`` on (the production default), each tenancy
    request gets a supervised agent process that OWNS the rendezvous dir
    and admits tenants against the claim's max-client / HBM budgets
    (tenancy_agent.py); Prepare blocks until the agent answers READY
    (AssertReady analog, sharing.go:322), and the claim's CDI spec
    injects a createContainer preflight hook so a DENIED admission fails
    the container start (tenancy_preflight.py). With it off (unit-test
    mode), only the env/mount contract is emitted.
    """

    def __init__(
        self,
        tenancy_root: str,
        hbm_capacity_bytes: int | None = None,
        spawn_agents: bool = False,
        ready_timeout: float = 10.0,
    ):
        self._root = os.path.join(tenancy_root, "tenancy")
        # Sibling of the tenancy root: reconcile() sweeps the tenancy
        # root's entries as claim uids and must never eat this dir.
        self._sock_dir = os.path.join(tenancy_root, "tenancy-sock")
        self._capacity = hbm_capacity_bytes
        self._spawn = spawn_agents
        self._ready_timeout = ready_timeout
        self._agents: dict[str, "object"] = {}  # dir -> ProcessManager
        # Concurrent sharded prepares/unprepares of different claims
        # mutate the agent map from different threads. _agents_lock
        # guards ONLY the map; the slow spawn/ready of one agent runs
        # under its per-dir lock so disjoint claims' tenancy setup
        # stays parallel (the point of the sharded pipeline).
        self._agents_lock = threading.Lock()
        self._dir_locks: dict[str, threading.Lock] = {}
        os.makedirs(self._root, exist_ok=True)

    def _dir_lock(self, d: str) -> threading.Lock:
        with self._agents_lock:
            lock = self._dir_locks.get(d)
            if lock is None:
                lock = self._dir_locks[d] = threading.Lock()
            return lock

    def _dir(self, claim_uid: str, request: str | None = None) -> str:
        d = os.path.join(self._root, claim_uid)
        return os.path.join(d, request) if request else d

    def start(
        self,
        claim_uid: str,
        request: str,
        chip_indices: list[int],
        cfg: MultiTenancyConfig,
        device_names: list[str],
    ) -> ContainerEdits:
        """Provision the per-request tenancy dir, start+await its agent,
        and emit workload env/mount/hook edits. One call per request
        group covers all its devices."""
        d = self._dir(claim_uid, request)
        # Only shared/ is bind-mounted (rw) into tenant containers --
        # the agent socket, grant state, and tombstones stay OUTSIDE the
        # mount, or a tenant could RELEASE a sibling's reservation and
        # defeat admission control (the protocol is unauthenticated; the
        # enforcement boundary is host-only reachability).
        shared = os.path.join(d, "shared")
        os.makedirs(shared, exist_ok=True)
        manifest = {
            "chips": chip_indices,
            "maxClients": cfg.max_clients,
            # PER-CHIP budget: every tenant of the group runs on every
            # chip of the group, so its per-chip demand applies to each
            # chip and admission must fit tenants within ONE chip's HBM
            # (multiplying by chip count would over-admit by that factor).
            "hbmCapacityBytes": self._capacity,
            "hbmLimits": {
                name: cfg.hbm_limit_bytes_for(name) for name in device_names
            },
        }
        write_json_atomic(os.path.join(d, "tenancy.json"), manifest)
        # Informational copy for tenants (the enforced one stays
        # host-side with the agent).
        write_json_atomic(os.path.join(shared, "tenancy.json"), manifest)
        env = [
            "TPU_MULTI_TENANT=1",
            f"TPU_TENANCY_DIR=/var/run/tpu-tenancy/{claim_uid}/{request}",
        ]
        if cfg.max_clients is not None:
            env.append(f"TPU_MAX_TENANTS={cfg.max_clients}")
        limits = [
            str(v) for v in manifest["hbmLimits"].values() if v is not None
        ]
        tenant_hbm = min(map(int, limits)) if limits else 0
        if limits:
            # Uniform per-group limit contract; per-device granularity
            # rides the manifest mount.
            env.append(f"TPU_HBM_LIMIT_BYTES={tenant_hbm}")
        edits = ContainerEdits(
            env=env,
            # Writable: co-tenant processes create rendezvous files here.
            # Only shared/ -- see the control/data split above.
            mounts=[(shared,
                     f"/var/run/tpu-tenancy/{claim_uid}/{request}", False)],
        )
        if self._spawn:
            d = self._short_dir(d)  # keep agent.sock inside sun_path
            self._ensure_agent(d)
            hook_path = self._hook_binary()
            base = [hook_path, "--dir", d]
            # OCI hook args include argv[0]. createContainer admits the
            # tenant (DENIED -> container start fails); poststop releases
            # its slot so a restarted container (fresh OCI id) never
            # leaks admissions.
            edits.hooks.append((
                "createContainer", hook_path,
                base + ["--hbm-bytes", str(tenant_hbm)],
            ))
            edits.hooks.append(("poststop", hook_path, base + ["--release"]))
        return edits

    def _hook_binary(self) -> str:
        """Host path of the preflight hook. The native static binary is
        copied into <root>/bin (a hostPath the runtime can exec -- the
        nvidia-cdi-hook copy pattern, gpu main.go:293); without it (dev
        checkouts) fall back to a wrapper script around this python."""
        bin_dir = os.path.join(os.path.dirname(self._root), "bin")
        os.makedirs(bin_dir, exist_ok=True)
        target = os.path.join(bin_dir, "tpu-tenancy-preflight")
        native = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tpulib", "native", "tenancy_preflight",
        )
        if os.path.exists(native):
            if (not os.path.exists(target)
                    or os.path.getmtime(target) < os.path.getmtime(native)):
                shutil.copy2(native, target + ".tmp")
                os.replace(target + ".tmp", target)
            return target
        # Dev fallback: exec this interpreter with the package on path.
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        script = (
            "#!/bin/sh\n"
            f'PYTHONPATH="{pkg_root}:$PYTHONPATH" exec "{sys.executable}" '
            "-m k8s_dra_driver_gpu_tpu.kubeletplugin.tenancy_preflight "
            '"$@"\n'
        )
        with open(target + ".tmp", "w", encoding="utf-8") as f:
            f.write(script)
        os.chmod(target + ".tmp", 0o755)
        os.replace(target + ".tmp", target)
        return target

    # -- agent supervision ------------------------------------------------------

    def _short_dir(self, d: str) -> str:
        """AF_UNIX sun_path caps at ~108 bytes; a long (legal) DRA
        request name can push <root>/tenancy/<uid>/<request>/agent.sock
        past it. Bind/connect through a short stable symlink instead
        (the kernel resolves it; the length limit applies only to the
        given string)."""
        import hashlib  # noqa: PLC0415

        os.makedirs(self._sock_dir, exist_ok=True)
        short = os.path.join(
            self._sock_dir, hashlib.md5(d.encode()).hexdigest()[:12])
        if os.path.realpath(short) != os.path.realpath(d):
            tmp = short + ".tmp"
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass
            os.symlink(d, tmp)
            os.replace(tmp, short)
        return short

    def _ensure_agent(self, d: str) -> None:
        """Start (or reuse) the agent owning dir ``d`` and block until it
        answers READY (AssertReady analog, sharing.go:322)."""
        from ..computedomain.daemon.process import (  # noqa: PLC0415
            ProcessManager,
        )
        from .tenancy_agent import query  # noqa: PLC0415

        # Per-dir lock: only same-dir callers serialize on the (slow)
        # fork/exec + readiness; disjoint claims spawn concurrently.
        with self._dir_lock(d):
            with self._agents_lock:
                pm = self._agents.get(d)
            if pm is None or not pm.alive():
                pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))))
                child_env = dict(os.environ)
                child_env["PYTHONPATH"] = (
                    pkg_root + os.pathsep + child_env.get("PYTHONPATH", "")
                ).rstrip(os.pathsep)
                # pidfile + PDEATHSIG (ProcessManager): a SIGKILLed plugin
                # can't leak agents, and a respawn kills any stale survivor
                # before the fresh agent rebinds agent.sock.
                pm = ProcessManager([
                    sys.executable, "-m",
                    "k8s_dra_driver_gpu_tpu.kubeletplugin.tenancy_agent",
                    "--dir", d,
                ], env=child_env, pidfile=os.path.join(d, "agent.pid"))
                pm.ensure_started()
                pm.start_watchdog()
                with self._agents_lock:
                    self._agents[d] = pm
        deadline = time.monotonic() + self._ready_timeout
        while time.monotonic() < deadline:
            try:
                if query(d, "STATUS", timeout=1.0) == "READY":
                    return
            except OSError:
                pass
            time.sleep(0.1)
        raise TenancyAgentError(
            f"tenancy agent for {d} not ready after {self._ready_timeout}s"
        )

    def reconcile(self, active_claim_uids: set[str]) -> None:
        """Plugin restart: re-own the tenancy dirs of still-prepared
        claims (respawn their agents) and drop orphans."""
        if not os.path.isdir(self._root):
            return
        for uid in os.listdir(self._root):
            if uid not in active_claim_uids:
                shutil.rmtree(os.path.join(self._root, uid),
                              ignore_errors=True)
                continue
            if self._spawn:
                claim_dir = os.path.join(self._root, uid)
                for request in os.listdir(claim_dir):
                    d = os.path.join(claim_dir, request)
                    if not os.path.isfile(os.path.join(d, "tenancy.json")):
                        continue
                    try:
                        self._ensure_agent(self._short_dir(d))
                    except TenancyAgentError:
                        # Claim-level failure: one unrecoverable tenancy
                        # dir must not crash-loop the whole node plugin.
                        # The claim's own retried Prepare (or unprepare)
                        # deals with it.
                        logger.exception(
                            "could not re-own tenancy agent for %s", d)
        # AFTER the orphan sweep (which may have just orphaned some):
        # drop dangling agent-socket symlinks.
        if os.path.isdir(self._sock_dir):
            for name in os.listdir(self._sock_dir):
                link = os.path.join(self._sock_dir, name)
                if os.path.islink(link) and not os.path.exists(link):
                    try:
                        os.unlink(link)
                    except OSError:
                        pass

    def stop(self, claim_uid: str) -> None:
        claim_dir = os.path.realpath(self._dir(claim_uid))
        # Claim the matching entries under the map lock, then stop the
        # processes outside it: a slow agent exit must not stall other
        # claims' setup/stop.
        mine: list[tuple[str, "object"]] = []
        with self._agents_lock:
            for d, pm in list(self._agents.items()):
                real = os.path.realpath(d)  # agents are keyed by short path
                if real.startswith(claim_dir + os.sep) or real == claim_dir:
                    del self._agents[d]
                    mine.append((d, pm))
        for d, pm in mine:
            with self._dir_lock(d):
                pm.stop()
            with self._agents_lock:
                # The dir is gone with the claim; drop its lock too or
                # a months-lived daemon leaks one lock per churned claim.
                self._dir_locks.pop(d, None)
            if os.path.islink(d):
                try:
                    os.unlink(d)
                except OSError:
                    pass
        shutil.rmtree(self._dir(claim_uid), ignore_errors=True)

    def agent_count(self) -> int:
        with self._agents_lock:
            return len(self._agents)

    def shutdown(self) -> None:
        """Stop every supervised agent (plugin shutdown; dirs stay --
        prepared claims survive plugin restarts via reconcile())."""
        with self._agents_lock:
            agents = list(self._agents.values())
            self._agents.clear()
        for pm in agents:
            pm.stop()

    def active(self, claim_uid: str) -> bool:
        return os.path.isdir(self._dir(claim_uid))
