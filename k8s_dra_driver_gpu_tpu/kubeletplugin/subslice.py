"""Sub-slice naming: spec tuples vs live tuples, canonical names.

Reference: cmd/gpu-kubelet-plugin/mig.go -- MigSpecTuple (abstract:
parent/placement/profile, :37) vs MigLiveTuple (concrete GIID/CIID/UUID,
:68), canonical-name regex parsers (:189,:236).

TPU canonical names:
    chip-<index>                          a whole chip
    chip-<index>-ss-<profile>-<placement> a sub-slice carve-out, e.g.
                                          chip-0-ss-1c-1 (TensorCore 1 of
                                          chip 0) or host-level block
                                          ss-<profile>-<placement> for
                                          multi-chip carve-outs, e.g.
                                          ss-2x1x1-2 (chips 2,3).

Chip-level profiles ("1c") nest under their parent chip; multi-chip
profiles are host-scoped (a carve-out spans chips, so no single parent).
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from ..tpulib.binding import SubSliceProfile, TpuHostInfo

_CHIP_RE = re.compile(r"^chip-(\d+)$")
_CHIP_SS_RE = re.compile(r"^chip-(\d+)-ss-([a-z0-9]+)-(\d+)$")
_HOST_SS_RE = re.compile(r"^ss-(\d+x\d+(?:x\d+)?)-(\d+)$")


def chip_name(index: int) -> str:
    return f"chip-{index}"


def parse_chip_name(name: str) -> int | None:
    m = _CHIP_RE.match(name)
    return int(m.group(1)) if m else None


@dataclass(frozen=True)
class SubSliceSpecTuple:
    """Abstract identity of a carve-out: profile + placement (+ parent
    chip for core-level profiles). Mirrors MigSpecTuple (mig.go:37)."""

    profile: str  # "1c" or a chip-grid shape like "2x1x1"
    placement: int  # core index (core-level) or start chip index
    parent_chip: int | None = None  # set for core-level profiles only

    @property
    def is_core_level(self) -> bool:
        return self.parent_chip is not None

    def canonical_name(self) -> str:
        if self.is_core_level:
            return f"chip-{self.parent_chip}-ss-{self.profile}-{self.placement}"
        return f"ss-{self.profile}-{self.placement}"

    @classmethod
    def from_canonical_name(cls, name: str) -> "SubSliceSpecTuple | None":
        m = _CHIP_SS_RE.match(name)
        if m:
            return cls(
                profile=m.group(2),
                placement=int(m.group(3)),
                parent_chip=int(m.group(1)),
            )
        m = _HOST_SS_RE.match(name)
        if m:
            return cls(profile=m.group(1), placement=int(m.group(2)))
        return None

    def chip_positions(self, host: TpuHostInfo) -> tuple[int, ...]:
        """Which GRID POSITIONS this carve-out occupies.

        Positions index host.chips (tpulib orders chips by position and
        assigns coords positionally), NOT raw accel indices -- on a host
        with a failed chip the two diverge. Callers map a position p to
        the physical chip via host.chips[p]."""
        if self.is_core_level:
            return (self.parent_chip,)
        dims = [int(d) for d in self.profile.split("x")]
        while len(dims) < 3:
            dims.append(1)
        w, h, d = dims
        hx, hy, _ = _host_grid(host)
        sx = self.placement % hx
        sy = (self.placement // hx) % hy
        sz = self.placement // (hx * hy)
        return tuple(
            ((sz + dz) * hy + (sy + dy)) * hx + (sx + dx)
            for dz in range(d)
            for dy in range(h)
            for dx in range(w)
        )

    def core_indices(self, host: TpuHostInfo) -> tuple[int, ...]:
        """Which cores (host-global, position-based core index) this
        carve-out occupies."""
        if self.is_core_level:
            return (self.parent_chip * host.cores_per_chip + self.placement
                    % host.cores_per_chip,)
        return tuple(
            c * host.cores_per_chip + k
            for c in self.chip_positions(host)
            for k in range(host.cores_per_chip)
        )


@dataclass(frozen=True)
class SubSliceLiveTuple:
    """A realized carve-out (what the runtime actually allocated).

    Mirrors MigLiveTuple (mig.go:68): spec + the concrete identity the
    device layer handed back (uuid; on TPU there is no GI/CI handle --
    the carve-out is realized by bounds env/devices at container start).
    """

    spec: SubSliceSpecTuple
    uuid: str

    def to_dict(self) -> dict:
        return {
            "profile": self.spec.profile,
            "placement": self.spec.placement,
            "parentChip": self.spec.parent_chip,
            "uuid": self.uuid,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "SubSliceLiveTuple":
        return cls(
            spec=SubSliceSpecTuple(
                profile=d["profile"],
                placement=d["placement"],
                parent_chip=d.get("parentChip"),
            ),
            uuid=d["uuid"],
        )


def _host_grid(host: TpuHostInfo) -> tuple[int, int, int]:
    """The local chip grid of this host (reduced when the host owns fewer
    chips than a full block), matching tpulib's placement indexing.

    Delegates to the tpulib backend's own grid helpers, and derives the
    grid from the TOPOLOGY (num_slice_chips / chips_per_host) rather than
    the live chip count, exactly as tpulib's subslice_profiles encodes
    placements -- a degraded host (failed chip) keeps the full grid and
    the missing positions simply have no backing chip."""
    from ..tpulib.binding import (  # noqa: PLC0415 - avoid import cycle
        _GENERATIONS,
        _host_shape,
        _slice_shape,
    )

    n = min(host.num_slice_chips, host.chips_per_host) or 1
    gen = _GENERATIONS.get(host.platform)
    if gen is None:
        return (1, n, 1)
    grid = _host_shape(gen)
    if n < grid[0] * grid[1] * grid[2]:
        grid = _slice_shape(gen, n)
    return grid


def enumerate_subslice_devices(
    host: TpuHostInfo, profiles: tuple[SubSliceProfile, ...]
) -> list[SubSliceSpecTuple]:
    """All possible carve-outs on this host (profile x placement),
    mirroring inspectMigProfilesAndPlacements (nvlib.go:1202)."""
    out: list[SubSliceSpecTuple] = []
    for prof in profiles:
        if prof.is_core_level:
            for placement in prof.placements:
                chip = placement // host.cores_per_chip
                core = placement % host.cores_per_chip
                out.append(
                    SubSliceSpecTuple(
                        profile=prof.name, placement=core, parent_chip=chip
                    )
                )
        else:
            for placement in prof.placements:
                out.append(
                    SubSliceSpecTuple(profile=prof.name, placement=placement)
                )
    return out
