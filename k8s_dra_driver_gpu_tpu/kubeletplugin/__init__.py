"""The per-node ``tpu.dra.dev`` DRA kubelet plugin.

Reference: cmd/gpu-kubelet-plugin/ (8318 LoC Go). Enumerates TPU chips
via tpulib, publishes ResourceSlices, serves NodePrepareResources /
NodeUnprepareResources with two-phase checkpointing, and injects devices
into containers via CDI specs.
"""

DRIVER_NAME = "tpu.dra.dev"
CDI_VENDOR = "k8s.tpu.dra.dev"
CDI_CLASS = "claim"
