"""Stale-claim GC: unprepare checkpointed claims the API server forgot.

Reference: cmd/gpu-kubelet-plugin/cleanup.go -- CheckpointCleanupManager:
every 10 minutes (:35) list checkpointed claims stuck in PrepareStarted
(or whose ResourceClaim no longer exists), validate against the API
server by namespace/name + UID (cheap Get, not List; :149-190), and
unprepare the stale ones; single-slot queue (:233).
"""

from __future__ import annotations

import logging
import threading

from ..pkg.kubeclient import NotFoundError
from .checkpoint import ClaimState

logger = logging.getLogger(__name__)

# Reference: every 10 min. Env override for operators tightening the
# reap latency (and the stale-claim GC system test).
from ..pkg import positive_float_env  # noqa: E402

DEFAULT_INTERVAL_S = positive_float_env(
    "TPU_DRA_CLEANUP_INTERVAL_S", default=600.0, floor=0.5)


def lookup_claim(kube, uid: str, namespace: str, name: str
                 ) -> tuple[str, dict | None]:
    """Validate a checkpointed claim identity against the API server
    (cheap Get, not List; cleanup.go:149-190). Returns one of:

      ("live", obj)      the object exists with the SAME uid
      ("gone", None)     deleted, or recreated under a new uid
      ("unknown", None)  no identity recorded / apiserver unavailable
                         -- callers must fail safe (keep state)

    Shared by the stale-claim GC and both reconcile sweeps so the
    staleness semantics can never drift apart."""
    if not namespace or not name:
        return "unknown", None
    try:
        obj = kube.get(
            "resource.k8s.io", "v1", "resourceclaims",
            name, namespace=namespace,
        )
    except NotFoundError:
        return "gone", None
    except Exception:  # noqa: BLE001 - apiserver unavailable: keep
        logger.exception("claim staleness check failed for %s/%s (%s)",
                         namespace, name, uid)
        return "unknown", None
    if obj.get("metadata", {}).get("uid") != uid:
        return "gone", None
    return "live", obj


class CheckpointCleanupManager:
    def __init__(
        self,
        device_state,
        kube_client,
        interval: float = DEFAULT_INTERVAL_S,
    ):
        self._state = device_state
        self._kube = kube_client
        self._interval = interval
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="checkpoint-cleanup", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread.ident is not None:  # join only a started thread
            self._thread.join(timeout=2.0)

    def cleanup_once(self, lookups=None) -> list[str]:
        """Returns the claim UIDs unprepared this pass. ``lookups``
        optionally carries precomputed ``lookup_claim`` results keyed
        by uid (the reconcile sweep shares one GET pass across its
        consumers); absent entries fall back to a fresh Get."""
        removed = []
        for uid, claim in list(self._state.prepared_claims().items()):
            if not self._is_stale(uid, claim, lookups):
                continue
            logger.warning(
                "unpreparing stale claim %s (%s/%s)",
                uid, claim.namespace, claim.name,
            )
            try:
                self._state.unprepare(uid)
                removed.append(uid)
            except Exception:  # noqa: BLE001 - GC must survive
                logger.exception("stale-claim unprepare failed for %s", uid)
        return removed

    def _is_stale(self, uid: str, claim, lookups=None) -> bool:
        """A claim is stale when its API object is gone or has a
        different UID (deleted + recreated under the same name)."""
        if not claim.namespace or not claim.name:
            # No identity recorded (crashed before v2 fields landed):
            # only PrepareStarted leftovers are safe to reap.
            return claim.state == ClaimState.PREPARE_STARTED.value
        hit = lookups.get(uid) if lookups else None
        if hit is None:
            hit = lookup_claim(self._kube, uid, claim.namespace,
                               claim.name)
        return hit[0] == "gone"

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.cleanup_once()
            except Exception:  # noqa: BLE001
                logger.exception("checkpoint cleanup pass failed")
