"""DeviceState: the node-side claim state machine.

Reference: cmd/gpu-kubelet-plugin/device_state.go (1328 LoC) -- idempotent
two-phase Prepare (PrepareStarted -> PrepareCompleted, :229-334), rollback
of partially prepared claims (:536), overlapping-allocation guard (:1212),
config precedence (class < claim, later wins; :1138), config dispatch to
sharing/sub-slice appliers (:1010), startup reconciliation of unknown
dynamic carve-outs (:388).

TPU specifics: a dynamic sub-slice "create" realizes the carve-out in the
node's live-sub-slice registry (the hardware-truth analog of the NVML MIG
walk -- TPU carve-outs are bounds handed to the runtime at container
start, so the registry is what crash recovery reconciles against) and
hands out a UUID; whole chips and core-level splits inject /dev/accel*
device nodes plus the TPU_* env contract via CDI.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid as uuidlib
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field

from ..api import configs as api_configs
from ..api.decode import strict_decode
from ..pkg.featuregates import (
    DYNAMIC_SUB_SLICE,
    MULTI_TENANCY_SUPPORT,
    PASSTHROUGH_SUPPORT,
    TENANT_PARTITIONING,
    TIME_SLICING_SETTINGS,
    FeatureGates,
)
from ..pkg.analysis.statemachine import TWO_PHASE_POLICY
from ..pkg.partition.engine import PartitionEngine, PartitionEngineError
from ..pkg.partition.spec import PartitionSet
from ..pkg import flightrecorder, tracing
from ..pkg.flock import Flock
from ..pkg.fsutil import write_json_atomic
from ..pkg.timing import SegmentTimer
from ..tpulib.binding import EnumerateOptions, TpuHostInfo, load as load_tpulib
from .cdi import CDIHandler, ContainerEdits
from .checkpoint import (
    CheckpointedClaim,
    CheckpointedDevice,
    CheckpointManager,
    ClaimState,
)
from .claim import ResourceClaim
from .deviceinfo import (
    AllocatableDevice,
    ChipInfo,
    DeviceKind,
    PassthroughInfo,
    SubSliceInfo,
)
from .vfio import VfioPciManager, VfioRegistry
from .sharing import MultiTenancyManager, TimeSlicingManager
from .subslice import (
    SubSliceLiveTuple,
    SubSliceSpecTuple,
    enumerate_subslice_devices,
)

logger = logging.getLogger(__name__)


class PrepareError(RuntimeError):
    pass


@dataclass
class Config:
    """Node plugin configuration."""

    root: str  # state root: checkpoint, CDI specs, policy files
    tpulib_opts: EnumerateOptions = field(default_factory=EnumerateOptions)
    feature_gates: FeatureGates = field(default_factory=FeatureGates)
    cdi_root: str | None = None
    boot_id: str | None = None
    # Run supervised per-claim tenancy agents (MPS-control-daemon analog).
    # Production default; mock configs default it off so unit tests don't
    # pay a child-process spawn per tenancy Prepare.
    tenancy_agents: bool = True
    # Admin-pre-carved static sub-slices (the static-MIG analog,
    # mig-parted style): canonical names like "ss-2x1x1-0" or
    # "chip-0-ss-1c-1". Published as-is; Prepare does not create (and
    # Unprepare does not destroy) a carve-out for them.
    static_subslices: tuple[str, ...] = ()
    # Desired multi-tenant partition layout (pkg/partition). Requires
    # the TenantPartitioning feature gate; None = no partition engine.
    partition_set: PartitionSet | None = None
    # Pool identity for PartitionSet pool globs (node-local pools are
    # named after the node); None = every PartitionSet applies.
    pool_name: str | None = None

    @classmethod
    def mock(
        cls,
        root: str,
        topology: str = "v5e-4",
        worker_id: int = 0,
        gates: str = "DynamicSubSlice=true,TimeSlicingSettings=true,"
        "MultiTenancySupport=true",
        tenancy_agents: bool = False,
        partition_set: PartitionSet | None = None,
    ) -> "Config":
        return cls(
            root=root,
            tpulib_opts=EnumerateOptions(
                mock_topology=topology, worker_id=worker_id
            ),
            feature_gates=FeatureGates.parse(gates),
            cdi_root=os.path.join(root, "cdi"),
            tenancy_agents=tenancy_agents,
            partition_set=partition_set,
        )


class SubSliceRegistry:
    """Node-local registry of live dynamic carve-outs (hardware truth for
    crash reconciliation; the analog of walking NVML for stray MIG
    devices, nvlib.go:420).

    The read-modify-write is flock-guarded: with the sharded prepare
    pipeline, carve-out creates for disjoint claims run concurrently
    (across threads AND processes during upgrade handover) and all land
    in this one file."""

    def __init__(self, root: str):
        self._path = os.path.join(root, "subslices.json")
        self._lock = Flock(self._path + ".lock")

    def list(self) -> dict[str, dict]:
        try:
            with open(self._path, encoding="utf-8") as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return {}

    def _write(self, entries: dict[str, dict]) -> None:
        # fsync: this registry is the crash-reconciliation source of
        # truth, so it gets the same durability as the checkpoint.
        tmp = self._path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(entries, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path)

    def create(self, live: SubSliceLiveTuple) -> None:
        with self._lock.acquire(timeout=10.0):
            entries = self.list()
            entries[live.uuid] = live.to_dict()
            self._write(entries)

    def destroy(self, uuid: str) -> None:
        with self._lock.acquire(timeout=10.0):
            entries = self.list()
            if entries.pop(uuid, None) is not None:
                self._write(entries)


def _proc_start_ticks(pid: int) -> int:
    """The process's starttime in clock ticks from /proc/<pid>/stat
    (field 22) -- the kernel's stable identity for a pid within one
    boot. 0 when the process doesn't exist (or /proc is unreadable)."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            data = f.read()
        # comm (field 2) is parenthesized and may itself contain spaces
        # and parens; split only after its LAST ')'. starttime is field
        # 22 overall = index 19 of the fields after state (field 3).
        rest = data[data.rindex(b")") + 2:].split()
        return int(rest[19])
    except (OSError, ValueError, IndexError):
        return 0


class _ReservationLeases:
    """Sidecar pid-leases for PrepareStarted reservations.

    Deliberately NOT part of checkpoint.json: extra fields in the v2
    payload would break cross-version checksum verification during
    upgrade handover (the issue-1080 class). A lease pins the pid +
    /proc starttime of the process whose prepare owns the reservation,
    so a same-claim retry in another process can distinguish a live
    peer's in-flight middle (fail retriable) from a crashed one (roll
    back) -- and a recycled pid reads as dead, never wedging the claim.
    A STARTED record with no lease is treated as crashed (that is also
    the pre-lease format's semantics). Written under the global
    reservation flock; advisory, so no fsync."""

    def __init__(self, root: str):
        self._dir = os.path.join(root, "leases")
        os.makedirs(self._dir, exist_ok=True)

    def _path(self, uid: str) -> str:
        return os.path.join(self._dir, f"{uid}.json")

    def write(self, uid: str) -> None:
        # Recreate the dir: boot-ID invalidation rmtree's it wholesale.
        os.makedirs(self._dir, exist_ok=True)
        pid = os.getpid()
        write_json_atomic(self._path(uid),
                          {"pid": pid, "start": _proc_start_ticks(pid)})

    def read(self, uid: str) -> tuple[int, int] | None:
        try:
            with open(self._path(uid), encoding="utf-8") as f:
                doc = json.load(f)
            return int(doc["pid"]), int(doc["start"])
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def clear(self, uid: str) -> None:
        try:
            os.unlink(self._path(uid))
        except FileNotFoundError:
            pass


class ShardedLocks:
    """Per-chip-position locks for the expensive middle of Prepare.

    Claims touching disjoint chips hold disjoint shard sets and run
    concurrently; shards are acquired in sorted order so overlapping
    holders (same-chip core-level carve-outs, unprepare vs. stale
    rollback) can never deadlock."""

    def __init__(self):
        self._locks: dict[int, threading.Lock] = {}
        self._mutex = threading.Lock()

    def _lock_for(self, shard: int) -> threading.Lock:
        with self._mutex:
            lock = self._locks.get(shard)
            if lock is None:
                lock = self._locks[shard] = threading.Lock()
            return lock

    # Bounded like the node flock's 10s (driver.go:381): a wedged
    # middle (hung vfio rebind, stuck tenancy agent) must fail later
    # same-chip operations with a clear error, not park kubelet's gRPC
    # threads on the lock forever.
    TIMEOUT_S = 10.0

    @contextmanager
    def hold(self, shards, timer: SegmentTimer | None = None):
        locks = [self._lock_for(s) for s in sorted(set(shards))]
        t0 = time.monotonic()
        deadline = t0 + self.TIMEOUT_S
        acquired: list[threading.Lock] = []
        try:
            for lock in locks:
                if not lock.acquire(
                        timeout=max(0.0, deadline - time.monotonic())):
                    raise PrepareError(
                        f"timed out after {self.TIMEOUT_S}s waiting for "
                        "chip shard lock (another claim's "
                        "prepare/unprepare is wedged on this chip)"
                    )
                acquired.append(lock)
            if timer is not None:
                timer.segments["prep_lock_wait"] = timer.segments.get(
                    "prep_lock_wait", 0.0) + (time.monotonic() - t0)
            yield
        finally:
            for lock in reversed(acquired):
                lock.release()


class DeviceState:
    """Prepare/Unprepare engine over this host's allocatable devices."""

    def __init__(self, config: Config):
        self._config = config
        os.makedirs(config.root, exist_ok=True)
        # Guards the short global reservation section and the in-flight
        # claim set; the expensive middle of Prepare runs under per-chip
        # shard locks instead (see prepare()).
        self._lock = threading.Lock()
        self._shards = ShardedLocks()
        self._inflight: set[str] = set()
        # Node-global reservation flock: excludes other plugin processes'
        # overlap-validation/reservation sections across upgrades
        # (reference driver.go:46-47). Held only for the reservation
        # critical section, not the whole prepare.
        self.pu_lock = Flock(os.path.join(config.root, "pu.lock"))
        # Sidecar pid-leases for in-flight PrepareStarted reservations
        # (kept out of checkpoint.json for cross-version checksum
        # compatibility).
        self._leases = _ReservationLeases(config.root)
        # Per-segment wall-time history (lock waits, fsync waits, ...)
        # for bench.py percentiles, plus an optional live observer
        # (pkg/metrics.py histogram, wired by the Driver).
        self._segment_history: dict[str, deque] = {}
        self._history_lock = threading.Lock()
        self.segment_observer = None  # callable(operation, segments) | None

        self._tpulib = load_tpulib()
        self.host: TpuHostInfo = self._tpulib.enumerate(config.tpulib_opts)
        self._profiles = self._tpulib.subslice_profiles(config.tpulib_opts)
        # Grid position of each physical chip (position == index on a
        # healthy host; they diverge when a chip is missing).
        self._pos_by_index = {
            chip.index: pos for pos, chip in enumerate(self.host.chips)
        }

        self._vfio = VfioPciManager(
            sys_root=config.tpulib_opts.sys_root or "/sys",
            dev_root=config.tpulib_opts.dev_root or "/dev",
            registry=VfioRegistry(config.root),
        )
        self.allocatable = self._enumerate_allocatable()
        # Two-phase lifecycle enforced at commit time: absent ->
        # PrepareStarted -> PrepareCompleted -> absent (statemachine
        # runtime validator; lint TPUDRA007 keeps this wired).
        self._checkpoint = CheckpointManager(
            config.root, boot_id=config.boot_id,
            transition_policy=TWO_PHASE_POLICY)
        self._registry = SubSliceRegistry(config.root)
        self._cdi = CDIHandler(
            cdi_root=config.cdi_root or os.path.join(config.root, "cdi")
        )
        self._timeslicing = TimeSlicingManager(config.root)
        self._tenancy = MultiTenancyManager(
            config.root,
            hbm_capacity_bytes=self.host.hbm_bytes_per_chip,
            spawn_agents=config.tenancy_agents,
        )

        if self._checkpoint.invalidated_on_boot:
            # A reboot destroyed all device state: the claim records are
            # gone, so the per-claim side state under the same persistent
            # root (sharing policies, tenancy dirs, CDI specs, live
            # carve-outs) must go with them or holder entries leak.
            self._cleanup_all_side_state()
        # Multi-tenant partition engine (pkg/partition): desired
        # partition devices join the allocatable set, and crashed
        # create/destroy records resolve BEFORE the unknown-state sweep
        # (the sweep consults the engine's live uuids, so a mid-
        # lifecycle carve-out is never read as an orphan).
        self.partition_engine: PartitionEngine | None = None
        if config.partition_set is not None and \
                config.feature_gates.is_enabled(TENANT_PARTITIONING):
            self.partition_engine = PartitionEngine(
                self, config.partition_set, pool=config.pool_name)
            self.allocatable.update(self.partition_engine.devices())
            self.partition_engine.resume()
        self.destroy_unknown_subslices()
        # Re-own tenancy state for claims that survived the restart
        # (respawn their enforcement agents; drop orphan dirs). A live
        # PEER's in-flight reservation (upgrade handover) counts as
        # active: its tenancy dir is mid-setup, not an orphan.
        self._tenancy.reconcile({
            uid for uid, c in self._checkpoint.get().claims.items()
            if c.state == ClaimState.PREPARE_COMPLETED.value
        } | self._live_foreign_reservations())

    def _live_foreign_reservations(self) -> set[str]:
        """Uids of PrepareStarted reservations owned by a LIVE peer
        plugin process (upgrade handover): their partial device state
        is in active mutation and must be left alone by sweeps."""
        return {
            uid for uid, c in self._checkpoint.get().claims.items()
            if c.state == ClaimState.PREPARE_STARTED.value
            and self._foreign_owner_alive(uid)
        }

    def stop(self) -> None:
        """Stop background machinery (supervised tenancy agents)."""
        self._tenancy.shutdown()

    def tenancy_agent_count(self) -> int:
        return self._tenancy.agent_count()

    # -- partition-engine collaborator surface --------------------------------
    # (public accessors so pkg/partition/engine.py never reaches into
    # underscore state; the registry alias keeps carve-out create/
    # destroy textually recognizable to lint rule TPUDRA011.)

    @property
    def config_root(self) -> str:
        return self._config.root

    @property
    def boot_id(self) -> str | None:
        return self._config.boot_id

    @property
    def subslice_profiles(self):
        return self._profiles

    @property
    def subslice_registry(self) -> SubSliceRegistry:
        return self._registry

    def apply_partition_set(self, partition_set: PartitionSet) -> None:
        """Swap in a new desired partition layout (profile-guided
        re-plan): the allocatable set is rebuilt atomically; callers
        republish slices afterwards.

        Partitions of RETIRED profiles that still have lifecycle
        records (live tenants, or a mid-flight teardown) stay in the
        allocatable set: overlap validation and the sharing-release
        math read their cores from here, so dropping them early would
        blind the node to cores a live workload occupies. New attaches
        to them already fail (the engine's desired set no longer knows
        the device); prune_retired_partitions() sweeps them out once
        their records are gone."""
        if self.partition_engine is None:
            raise PrepareError("partition engine not enabled")
        devices = self.partition_engine.apply(partition_set)
        held = self.partition_engine.recorded_devices()
        with self._lock:
            merged = {
                name: dev for name, dev in self.allocatable.items()
                if dev.kind != DeviceKind.PARTITION
                or (name in held and name not in devices)
            }
            merged.update(devices)
            self.allocatable = merged

    def prune_retired_partitions(self) -> int:
        """Drop partition devices that are neither desired nor backed
        by a lifecycle record anymore (a re-plan retired them and their
        last tenant has since detached). Returns devices pruned; the
        next publish drops them from the ResourceSlices."""
        if self.partition_engine is None:
            return 0
        desired = set(self.partition_engine.devices())
        held = self.partition_engine.recorded_devices()
        with self._lock:
            retired = [
                name for name, dev in self.allocatable.items()
                if dev.kind == DeviceKind.PARTITION
                and name not in desired and name not in held
            ]
            if retired:
                merged = dict(self.allocatable)
                for name in retired:
                    del merged[name]
                self.allocatable = merged
        return len(retired)

    # -- enumeration ----------------------------------------------------------

    def _enumerate_allocatable(self) -> dict[str, AllocatableDevice]:
        out: dict[str, AllocatableDevice] = {}
        for chip in self.host.chips:
            info = ChipInfo(chip=chip, host=self.host)
            out[info.canonical_name] = AllocatableDevice(
                kind=DeviceKind.CHIP, chip=info
            )
        expected = min(self.host.num_slice_chips, self.host.chips_per_host)
        degraded = len(self.host.chips) < expected
        if degraded:
            # A host missing chips keeps publishing the survivors as
            # whole chips (taints mark the gap) but offers no carve-outs:
            # the placement grid can't be trusted against a hole.
            logger.warning(
                "degraded host (%d/%d chips): not publishing sub-slices",
                len(self.host.chips), expected,
            )
        if self._config.feature_gates.is_enabled(PASSTHROUGH_SUPPORT):
            for chip in self.host.chips:
                group = self._vfio.iommu_group(chip.pci_bdf)
                if group < 0:
                    # No IOMMU group: the device could never be prepared
                    # for passthrough, so don't let a scheduler pick it.
                    logger.warning(
                        "chip %s has no iommu group: not publishing a "
                        "passthrough device", chip.pci_bdf,
                    )
                    continue
                info = PassthroughInfo(
                    chip=chip, host=self.host, iommu_group=group,
                )
                out[info.canonical_name] = AllocatableDevice(
                    kind=DeviceKind.PASSTHROUGH, passthrough=info
                )
        all_specs = (
            enumerate_subslice_devices(self.host, self._profiles)
            if not degraded else []
        )
        if self._config.feature_gates.is_enabled(DYNAMIC_SUB_SLICE):
            for spec in all_specs:
                # Full-host carve-outs duplicate the chip set; still
                # published (schedulers pick by shape), reference
                # publishes the full-GPU MIG profile too.
                info = SubSliceInfo(spec=spec, host=self.host, dynamic=True)
                out[info.canonical_name] = AllocatableDevice(
                    kind=DeviceKind.SUBSLICE_DYNAMIC, subslice=info
                )
        if self._config.static_subslices:
            if degraded:
                # Like the dynamic path: a host missing chips cannot
                # trust the placement grid -- keep the surviving whole
                # chips published and warn, never crash-loop the plugin
                # over a carve-out it can't honor right now.
                logger.warning(
                    "degraded host: not publishing static sub-slices %s",
                    list(self._config.static_subslices),
                )
            else:
                valid = {s.canonical_name() for s in all_specs}
                for name in self._config.static_subslices:
                    if name not in valid:
                        # A bad NAME is a config error on a healthy
                        # host: fail startup loudly rather than
                        # silently publishing less than declared.
                        raise ValueError(
                            f"static sub-slice {name!r} is not a valid "
                            f"carve-out for this host "
                            f"({self.host.accelerator_type or 'unknown'})"
                        )
                    spec = SubSliceSpecTuple.from_canonical_name(name)
                    info = SubSliceInfo(spec=spec, host=self.host,
                                        dynamic=False)
                    # Static wins over the identically-named dynamic
                    # device: the admin carved it; it must not be torn
                    # down at Unprepare.
                    out[info.canonical_name] = AllocatableDevice(
                        kind=DeviceKind.SUBSLICE_STATIC, subslice=info
                    )
        return out

    def _cleanup_all_side_state(self) -> None:
        import shutil  # noqa: PLC0415

        for sub in ("timeslice", "tenancy", "leases"):
            shutil.rmtree(os.path.join(self._config.root, sub),
                          ignore_errors=True)
        os.makedirs(os.path.join(self._config.root, "timeslice"), exist_ok=True)
        os.makedirs(os.path.join(self._config.root, "tenancy"), exist_ok=True)
        cdi_root = self._config.cdi_root or os.path.join(self._config.root, "cdi")
        if os.path.isdir(cdi_root):
            for name in os.listdir(cdi_root):
                if name.startswith("k8s.tpu.dra.dev-claim_"):
                    try:
                        os.unlink(os.path.join(cdi_root, name))
                    except OSError:
                        pass
        # Live carve-outs all belonged to pre-reboot claims.
        for live_uuid in list(self._registry.list()):
            self._registry.destroy(live_uuid)
        logger.warning("boot-ID change: cleared all per-claim side state")

    # -- crash reconciliation -------------------------------------------------

    def destroy_unknown_subslices(self) -> int:
        """Tear down live carve-outs AND orphaned vfio rebinds not
        referenced by any checkpointed claim (checkpoint is source of
        truth; device_state.go:388).

        Deferred wholesale while ANY prepare is in flight -- a LIVE
        peer process's (upgrade handover) or this process's own (the
        periodic reconcile sweep runs concurrently with served
        prepares): a mid-middle prepare has created its carve-out but
        its durable record is still the live-less PrepareStarted
        reservation, so the carve-out would read as an orphan. The
        whole audit runs under ``self._lock`` with the in-flight check
        LAST-WRITER-WINS safe: a prepare registers in ``_inflight``
        inside the reservation section (under this same lock) BEFORE
        it can create any carve-out, so an empty in-flight set under
        the lock guarantees every registry entry seen here belongs to
        a settled claim state. True orphans are swept on the next
        pass, once nothing is in flight."""
        live_peers = self._live_foreign_reservations()
        if live_peers:
            logger.warning(
                "deferring unknown-state sweep: claim(s) %s are mid-"
                "prepare in a live peer plugin process",
                sorted(live_peers),
            )
            return 0
        with self._lock:
            if self._inflight:
                logger.info(
                    "deferring unknown-state sweep: %d prepare/"
                    "unprepare operation(s) in flight in this process",
                    len(self._inflight),
                )
                return 0
            cp = self._checkpoint.get()
            referenced = {
                dev.live["uuid"]
                for c in cp.claims.values()
                for dev in c.devices
                if dev.live and "uuid" in dev.live  # vfio: no uuid
            }
            if self.partition_engine is not None:
                # Partition carve-outs mid-lifecycle (Creating/Ready/
                # Destroying records) are owned by the engine, not by
                # claim records alone.
                referenced |= self.partition_engine.live_uuids()
            destroyed = 0
            for uid in list(self._registry.list()):
                if uid not in referenced:
                    self._registry.destroy(uid)
                    destroyed += 1
            # Orphaned passthrough rebinds: a crash between configure()
            # and the completed checkpoint leaves the chip on vfio-pci
            # with no claim record; the vfio registry lets us rebind it
            # back.
            claimed_bdfs = {
                dev.live["pciBdf"]
                for c in cp.claims.values()
                for dev in c.devices
                if dev.live and dev.live.get("vfio")
            }
            if self._vfio.registry is not None:
                for bdf in list(self._vfio.registry.list()):
                    if bdf not in claimed_bdfs:
                        logger.warning(
                            "unbinding orphaned vfio rebind of %s", bdf)
                        self._vfio.unconfigure(bdf)
                        destroyed += 1
        if destroyed:
            logger.warning(
                "reconciled %d unknown sub-slice(s)/rebind(s)", destroyed
            )
        return destroyed

    # -- prepare --------------------------------------------------------------

    def prepare(self, claim: ResourceClaim) -> list[str]:
        """Idempotent two-phase prepare; returns CDI device IDs.

        Locking hierarchy (disjoint claims prepare in PARALLEL):

        1. **Global reservation section** -- node flock (excludes other
           plugin processes, reference driver.go:381) + process lock,
           held only for overlap validation, config resolution, and the
           durable PrepareStarted record. The record carries the claim's
           device names, so a competing validation (this process or
           another) sees the reservation the instant the lock drops.
        2. **Per-chip shard locks** -- the expensive middle (carve-out
           create, sharing setup, CDI spec write) runs under the locks
           of just the chips the claim touches.
        3. **Group-committed checkpoint writes** -- concurrent claims
           share fsyncs (see CheckpointManager).

        Per-segment wall times are logged at debug level (the t_prep_*
        instrumentation, reference driver.go:394-404); ``prep_lock_wait``
        and ``ckpt_fsync_wait`` also feed the metrics histogram and
        bench.py's stress extras.
        """
        # Cross-binary trace: the scheduler's commit span context rides
        # the claim's traceparent annotation, so every segment below
        # becomes a child span of that commit (pkg/tracing.py). A
        # claim with no (or an unsampled) annotation traces locally.
        timer = SegmentTimer("prepare", claim.uid,
                             parent=tracing.extract(claim.annotations))
        try:
            return self._prepare_inner(claim, timer)
        finally:
            # Failed/slow/idempotent prepares need the breakdown most.
            self._record_segments(timer)
            timer.done()

    def _prepare_inner(self, claim: ResourceClaim, timer: SegmentTimer
                       ) -> list[str]:
        t0 = time.monotonic()
        # Keep acquisition inside the with-statement: pulling the
        # guard out would open an async-exception window where the
        # non-reentrant flock leaks held.
        with self.pu_lock.acquire(timeout=10.0), self._lock:
            timer.segments["prep_lock_wait"] = time.monotonic() - t0
            if claim.uid in self._inflight:
                raise PrepareError(
                    f"claim {claim.uid} prepare already in flight"
                )
            with timer.segment("prep_get_checkpoint"):
                cp = self._checkpoint.get()
            existing = cp.claims.get(claim.uid)
            if (existing
                    and existing.state == ClaimState.PREPARE_COMPLETED.value):
                # Idempotent return ONLY if the (un-fsync'd,
                # regenerable) CDI spec actually survived; a
                # crash-truncated spec falls through to a full
                # re-prepare.
                try:
                    spec_ok = self._cdi.read_spec(claim.uid) is not None
                except ValueError:
                    spec_ok = False  # corrupt JSON
                if spec_ok:
                    return [
                        i for d in existing.devices
                        for i in d.cdi_device_ids
                    ]
                # Regenerating via rollback+re-prepare is only safe
                # when it can't disturb state a RUNNING workload may
                # hold: vfio rebinds and tenancy rendezvous dirs
                # must not be torn down under a live pod.
                disruptive = any(
                    d.live and d.live.get("vfio")
                    for d in existing.devices
                ) or self._tenancy.active(claim.uid)
                if disruptive:
                    logger.error(
                        "claim %s completed but CDI spec missing/"
                        "corrupt; NOT re-preparing (live vfio/"
                        "tenancy state) -- unprepare to recover",
                        claim.uid,
                    )
                    return [
                        i for d in existing.devices
                        for i in d.cdi_device_ids
                    ]
                logger.warning(
                    "claim %s completed but CDI spec missing/corrupt; "
                    "re-preparing", claim.uid,
                )
                # Under the record's chip shards: another claim's
                # middle on a shared chip must not interleave with
                # this teardown (same invariant as unprepare). Shard
                # holders never wait on the global locks we hold, so
                # the ordering is deadlock-free.
                with timer.segment("prep_rollback_stale"), \
                        self._shards.hold(
                            self._shards_of_checkpointed(existing), timer):
                    self._rollback(existing)
            if (existing
                    and existing.state == ClaimState.PREPARE_STARTED.value):
                # A reservation from a prepare that isn't OURS (our own
                # in-flight one was rejected above). If the lease's
                # owner process is still alive -- upgrade handover with
                # a kubelet retry racing the old plugin's live middle --
                # rolling back would destroy state that process is
                # actively mutating: fail retriable instead. Only a
                # DEAD owner's partial state is rolled back
                # (device_state.go:277).
                owner = self._foreign_owner_alive(claim.uid)
                if owner:
                    raise PrepareError(
                        f"claim {claim.uid} prepare in progress in "
                        f"plugin process {owner}; retry"
                    )
                with timer.segment("prep_rollback_stale"), \
                        self._shards.hold(
                            self._shards_of_checkpointed(existing), timer):
                    self._rollback(existing)

            self._validate_no_overlap(cp, claim)

            # Resolve + validate configs BEFORE the PrepareStarted
            # write: a claim with a bad config now fails without
            # ever touching the checkpoint (no write+rollback pair).
            cfgs = self._resolve_configs(claim)

            # The PrepareStarted record doubles as the RESERVATION:
            # recording the device names here makes the claim's chips
            # visible to every later overlap validation while the
            # expensive middle runs outside the global lock.
            reservation = CheckpointedClaim(
                uid=claim.uid,
                namespace=claim.namespace,
                name=claim.name,
                state=ClaimState.PREPARE_STARTED.value,
                devices=[
                    CheckpointedDevice(
                        canonical_name=r.device,
                        kind=self._known_kind(r.device),
                    )
                    for r in claim.results
                ],
            )
            # Lease first, then the durable record: a crash in between
            # leaves an orphan lease that the next writer overwrites.
            self._leases.write(claim.uid)
            with timer.segment("checkpoint_write_started"):
                self._checkpoint.update_claim(
                    claim.uid, reservation, timer=timer)
            # Fault-injection seam INSIDE the reservation section,
            # after the durable PrepareStarted write (the handover and
            # crash-sweep system tests hook it).
            with timer.segment("prep_reserved"):
                pass
            # Compute shards BEFORE registering in flight: a raise here
            # must not leave the uid stuck in _inflight (the discard in
            # the finally below isn't armed yet).
            shards = self._shards_of_claim(claim)
            self._inflight.add(claim.uid)

        try:
            with self._shards.hold(shards, timer):
                try:
                    with timer.segment("prep_devices"):
                        prepared = self._prepare_devices(claim, timer, cfgs)
                except BaseException:
                    # _prepare_devices rolled back its own partial device
                    # state; drop the PrepareStarted reservation.
                    self._checkpoint.update_claim(claim.uid, None)
                    self._leases.clear(claim.uid)
                    raise

                completed = CheckpointedClaim(
                    uid=claim.uid,
                    namespace=claim.namespace,
                    name=claim.name,
                    state=ClaimState.PREPARE_COMPLETED.value,
                    devices=prepared,
                )
                with timer.segment("checkpoint_write_completed"):
                    self._checkpoint.update_claim(
                        claim.uid, completed, timer=timer)
                self._leases.clear(claim.uid)
                return [i for d in prepared for i in d.cdi_device_ids]
        finally:
            with self._lock:
                self._inflight.discard(claim.uid)

    def _foreign_owner_alive(self, claim_uid: str) -> int:
        """The live foreign owner pid of a PrepareStarted reservation,
        or 0. Our own pid can't be a live foreign owner: a record we
        didn't register in _inflight is a crashed predecessor's. The
        /proc starttime pins the process IDENTITY -- a recycled pid
        (same number, different process) reads as dead, so a stale
        reservation can't wedge the claim. Plugin pods must share the
        host pid namespace (hostPID: true in the chart), as the
        handover flock already requires a shared state root."""
        lease = self._leases.read(claim_uid)
        if lease is None:
            return 0  # no lease = pre-lease writer or crashed mid-write
        pid, start = lease
        if not pid or pid == os.getpid():
            return 0
        current_start = _proc_start_ticks(pid)
        if current_start == 0 or (start and start != current_start):
            return 0  # dead, or the pid was recycled
        return pid

    def _known_kind(self, canonical_name: str) -> str:
        """Device kind for the reservation record; rejects unknown
        devices BEFORE the PrepareStarted write (no write+rollback
        pair for a claim that could never prepare)."""
        dev = self.allocatable.get(canonical_name)
        if dev is None:
            raise PrepareError(f"unknown device {canonical_name!r}")
        return dev.kind.value

    def _shards_of_claim(self, claim: ResourceClaim) -> set[int]:
        """Chip-position shard set of a claim. Core-level carve-outs on
        one chip share its shard (their sharing-policy files are
        per-chip); distinct chips never contend."""
        shards: set[int] = set()
        for result in claim.results:
            for core in self._cores_of(result.device):
                shards.add(core // self.host.cores_per_chip)
        return shards

    def _shards_of_checkpointed(self, checkpointed: CheckpointedClaim
                                ) -> set[int]:
        shards: set[int] = set()
        for dev in checkpointed.devices:
            for core in self._cores_of(dev.canonical_name):
                shards.add(core // self.host.cores_per_chip)
        return shards

    def _record_segments(self, timer: SegmentTimer) -> None:
        with self._history_lock:
            for name, dt in timer.segments.items():
                self._segment_history.setdefault(
                    name, deque(maxlen=4096)).append(dt)
        # The per-claim flight recorder gets the same breakdown the
        # histogram sees, keyed by claim UID and tied to the trace.
        if timer.key:
            flightrecorder.default().record(
                timer.key, f"{timer.operation}_segments",
                trace_id=timer.trace_id,
                **{f"{name}_ms": round(dt * 1e3, 2)
                   for name, dt in sorted(timer.segments.items())})
        observer = self.segment_observer
        if observer is not None:
            try:
                observer(timer.operation, dict(timer.segments))
            except Exception:  # noqa: BLE001 - metrics must not kill prepare
                logger.exception("segment observer failed")

    def segment_samples(self, name: str) -> list[float]:
        """Recent wall-time samples (seconds) of one timer segment."""
        with self._history_lock:
            return list(self._segment_history.get(name, ()))

    def _slots_of(self, canonical_name: str) -> int:
        """Tenant-slot count of a device: oversubscribed partition
        devices admit up to maxTenants concurrent claims; everything
        else is exclusive (1)."""
        dev = self.allocatable.get(canonical_name)
        if dev is not None and dev.kind == DeviceKind.PARTITION and \
                dev.partition is not None:
            return dev.partition.profile.max_tenants
        return 1

    def _validate_no_overlap(self, cp, claim: ResourceClaim) -> None:
        """Reject preparing a device whose chips/cores another claim holds
        (guards scheduler races; device_state.go:1212-1249).

        PrepareStarted claims count too: their device list is the
        RESERVATION an in-flight prepare wrote before leaving the global
        section (legacy records without devices can't conflict).

        Oversubscribed partition devices (pkg/partition) are the one
        sanctioned overlap: up to ``maxTenants`` claims may hold the
        SAME device (they cooperatively share its cores), but its cores
        still exclude every OTHER device, and the slot budget is a hard
        cap -- the node-side mirror of the scheduler's slot-aware
        allocation."""
        held: dict[int, tuple[str, str]] = {}  # core -> (device, uid)
        holders: dict[str, set[str]] = {}  # device -> holder uids
        for other in cp.claims.values():
            if other.uid == claim.uid:
                continue
            for dev in other.devices:
                holders.setdefault(dev.canonical_name, set()).add(
                    other.uid)
                for core in self._cores_of(dev.canonical_name):
                    held[core] = (dev.canonical_name, other.uid)
        for result in claim.results:
            slots = self._slots_of(result.device)
            if slots > 1:
                already = holders.get(result.device, set())
                if len(already) >= slots:
                    raise PrepareError(
                        f"device {result.device} has no free tenant "
                        f"slot ({len(already)}/{slots} held)"
                    )
                for core in self._cores_of(result.device):
                    entry = held.get(core)
                    if entry is not None and entry[0] != result.device:
                        raise PrepareError(
                            f"device {result.device} overlaps with "
                            f"prepared claim {entry[1]} (device "
                            f"{entry[0]})"
                        )
                continue
            for core in self._cores_of(result.device):
                if core in held:
                    raise PrepareError(
                        f"device {result.device} overlaps with prepared "
                        f"claim {held[core][1]}"
                    )

    def _cores_of(self, canonical_name: str) -> tuple[int, ...]:
        """Position-based core set of a device (for overlap math).

        Uses grid POSITIONS, not raw accel indices, so whole-chip and
        carve-out claims account against the same coordinate system even
        when device indices are sparse."""
        dev = self.allocatable.get(canonical_name)
        if dev is None:
            return ()
        if dev.kind == DeviceKind.CHIP or dev.kind == DeviceKind.PASSTHROUGH:
            chip = (dev.chip or dev.passthrough).chip
            pos = self._pos_by_index[chip.index]
            return tuple(
                pos * self.host.cores_per_chip + k
                for k in range(self.host.cores_per_chip)
            )
        if dev.partition is not None:
            return dev.partition.spec.core_indices(self.host)
        if dev.subslice is not None:
            return dev.subslice.spec.core_indices(self.host)
        return ()

    def _chips_at(self, positions: tuple[int, ...]):
        """Physical chips backing grid positions (PrepareError when a
        position has no live chip)."""
        chips = []
        for pos in positions:
            if pos >= len(self.host.chips):
                raise PrepareError(
                    f"grid position {pos} has no backing chip on this host"
                )
            chips.append(self.host.chips[pos])
        return chips

    def _subslice_contract(self, spec, edits) -> list:
        """Device nodes + TPU bounds env for a sub-slice-backed device.
        ONE contract shared by dynamic/static sub-slices and partition
        carve-outs -- a bounds-format change edited here reaches every
        tenant kind. Returns the backing physical chips."""
        positions = (
            spec.chip_positions(self.host)
            if not spec.is_core_level
            else (spec.parent_chip,)
        )
        physical = self._chips_at(positions)
        for chip in physical:
            edits.device_nodes.append(chip.devpath)
        if spec.is_core_level:
            edits.env.append(f"TPU_CORE_BOUNDS={spec.placement}")
            edits.env.append("TPU_MEGACORE=disabled")
        else:
            edits.env.append(
                f"TPU_CHIPS_PER_HOST_BOUNDS={spec.profile.replace('x', ',')}"
            )
        return physical

    def _resolve_configs(self, claim: ResourceClaim):
        """Per-request effective config: class-sourced first, claim-sourced
        later, later wins (GetOpaqueDeviceConfigs precedence :1138; a
        default TpuConfig/SubSliceConfig is injected when nothing matches
        :698-724). Resolved once per unique request."""
        ordered = [c for c in claim.configs if c.source == "FromClass"] + [
            c for c in claim.configs if c.source != "FromClass"
        ]
        first_device: dict[str, str] = {}
        for result in claim.results:
            first_device.setdefault(result.request, result.device)
        per_request: dict[str, object] = {}
        for request, device in first_device.items():
            winner = None
            for oc in ordered:
                if oc.applies_to(request):
                    winner = oc
            if winner is not None:
                cfg_obj = strict_decode(winner.parameters)
            else:
                dev = self.allocatable.get(device)
                if dev is not None and dev.kind in (
                    DeviceKind.SUBSLICE_DYNAMIC,
                    DeviceKind.SUBSLICE_STATIC,
                    DeviceKind.PARTITION,
                ):
                    cfg_obj = api_configs.SubSliceConfig()
                elif dev is not None and dev.kind == DeviceKind.PASSTHROUGH:
                    cfg_obj = api_configs.PassthroughConfig()
                else:
                    cfg_obj = api_configs.TpuConfig()
            cfg_obj.normalize()
            cfg_obj.validate()
            per_request[request] = cfg_obj
        return per_request

    def _prepare_devices(
        self, claim: ResourceClaim, timer: SegmentTimer, cfgs=None
    ) -> list[CheckpointedDevice]:
        """All-or-nothing: any failure rolls back the partial device state
        created by this attempt (carve-outs, sharing state, CDI spec)
        before re-raising (unpreparePartiallyPrepairedClaim analog,
        device_state.go:536)."""
        created_live: list[str] = []
        configured_vfio: list[str] = []
        attached_parts: list[str] = []
        touched_chips: set[int] = set()
        try:
            return self._prepare_devices_inner(
                claim, created_live, configured_vfio, attached_parts,
                touched_chips, timer, cfgs,
            )
        except BaseException:
            for live_uuid in created_live:
                self._registry.destroy(live_uuid)
            for name in attached_parts:
                if self.partition_engine is not None:
                    # Holder-counted: the backing carve-out survives if
                    # a co-tenant claim still holds the partition. A
                    # detach failure here must not mask the original
                    # error -- the durable Destroying record makes the
                    # next sweep/retry finish it.
                    try:
                        self.partition_engine.detach(claim.uid, name)
                    except PartitionEngineError:
                        logger.exception(
                            "rollback: partition detach failed for %s "
                            "(will resume from the durable record)",
                            name)
            for bdf in configured_vfio:
                self._vfio.unconfigure(bdf)
            self._timeslicing.release(claim.uid, sorted(touched_chips))
            self._tenancy.stop(claim.uid)
            self._cdi.delete_claim_spec_file(claim.uid)
            raise

    def _prepare_devices_inner(
        self,
        claim: ResourceClaim,
        created_live: list[str],
        configured_vfio: list[str],
        attached_parts: list[str],
        touched_chips: set[int],
        timer: SegmentTimer,
        cfgs=None,
    ) -> list[CheckpointedDevice]:
        if cfgs is None:
            cfgs = self._resolve_configs(claim)
        prepared: list[CheckpointedDevice] = []
        device_edits: dict[str, ContainerEdits] = {}
        # canonical device name -> CDI device name. Usually identity;
        # oversubscribed partition devices get a claim-scoped CDI name,
        # because N tenant claims hold the SAME canonical device and
        # qualified CDI ids (vendor/class=name) must stay unique across
        # their transient specs.
        cdi_name_of: dict[str, str] = {}
        claim_chips: set[int] = set()
        # request -> (chips, device names) for one sharing application per
        # config group (the reference merges MPS edits per group,
        # cdi.go:181-307).
        groups: dict[str, tuple[set[int], list[str]]] = {}

        for result in claim.results:
            dev = self.allocatable.get(result.device)
            if dev is None:
                raise PrepareError(f"unknown device {result.device!r}")
            cfg = cfgs[result.request]
            self._check_config_kind(dev, cfg)

            edits = ContainerEdits()
            live = None
            if dev.kind == DeviceKind.CHIP:
                physical = [dev.chip.chip]
                edits.device_nodes.append(dev.chip.chip.devpath)
            elif dev.kind == DeviceKind.PASSTHROUGH:
                chip = dev.passthrough.chip
                physical = [chip]
                # Kernel boundary: rebind to vfio-pci (vfio-device.go:145).
                # Record BEFORE configuring: a failure mid-rebind must
                # still be rolled back (unconfigure is idempotent).
                configured_vfio.append(chip.pci_bdf)
                edits = edits.merge(
                    self._vfio.configure(chip.pci_bdf, cfg)
                )
                live = {"pciBdf": chip.pci_bdf, "vfio": True}
            elif dev.kind == DeviceKind.PARTITION:
                info = dev.partition
                if self.partition_engine is None:
                    raise PrepareError(
                        "partition engine not enabled on this node"
                    )
                if info.oversubscribed and not getattr(
                        cfg, "oversubscribe", False):
                    raise PrepareError(
                        f"device {result.device} is oversubscribed "
                        f"({info.profile.max_tenants} tenant slots); "
                        "the claim's SubSliceConfig must opt in with "
                        "oversubscribe: true"
                    )
                physical = self._subslice_contract(info.spec, edits)
                edits.env.append(f"TPU_PARTITION={info.profile.name}")
                edits.env.append(
                    f"TPU_PARTITION_HBM_BYTES={info.tenant_hbm_bytes}")
                # Carve-out realized on demand (first tenant creates,
                # co-tenants attach); crash-resumable via the engine's
                # partition records.
                try:
                    with timer.segment("prep_attach_partition"):
                        live = self.partition_engine.attach(
                            claim.uid, result.device)
                except PartitionEngineError as e:
                    raise PrepareError(str(e)) from e
                attached_parts.append(result.device)
            else:
                ss = dev.subslice
                physical = self._subslice_contract(ss.spec, edits)
                if dev.kind == DeviceKind.SUBSLICE_DYNAMIC:
                    live_t = SubSliceLiveTuple(
                        spec=ss.spec, uuid=f"tpu-ss-{uuidlib.uuid4()}"
                    )
                    # HOT path analog of createMigDevice (nvlib.go:926).
                    with timer.segment("prep_create_subslice"):
                        self._registry.create(live_t)
                    created_live.append(live_t.uuid)
                    live = live_t.to_dict()

            physical_idxs = [c.index for c in physical]
            claim_chips.update(physical_idxs)
            grp = groups.setdefault(result.request, (set(), []))
            grp[0].update(physical_idxs)
            grp[1].append(result.device)

            # Additive per-chip markers: a pod consuming SEVERAL claims
            # gets every claim's CDI spec applied, and same-name env
            # (TPU_VISIBLE_DEVICES below) merges last-wins under CDI --
            # unique names merge as the union, so consumers can always
            # recover the full visible set (mock_workload_site does).
            for i in physical_idxs:
                edits.env.append(f"TPU_DEVICE_{i}=1")

            cdi_name = result.device
            if self._slots_of(result.device) > 1:
                cdi_name = f"{result.device}-t-{claim.uid}"
            cdi_name_of[result.device] = cdi_name
            device_edits[cdi_name] = edits
            prepared.append(
                CheckpointedDevice(
                    canonical_name=result.device,
                    kind=dev.kind.value,
                    cdi_device_ids=[],
                    live=live,
                )
            )

        # One sharing application per request group over its full chip and
        # device set. Groups holding oversubscribed partition devices
        # get the partition-engine sharing contract instead (time-slice
        # policy + per-tenant tenancy enforcement).
        sharing_edits = ContainerEdits()
        for request, (chips, names) in groups.items():
            over = [n for n in names if self._slots_of(n) > 1]
            if over:
                if len(over) != len(names):
                    # Fail closed: applying the partition sharing
                    # contract (time-slice policy + per-slot HBM
                    # ceiling) across the group would wrongly cap the
                    # exclusive devices, and skipping it would leave
                    # the shared ones unenforced. A class selector
                    # matching both shapes must be split into separate
                    # requests.
                    raise PrepareError(
                        f"request {request!r} mixes oversubscribed "
                        f"partition devices ({sorted(over)}) with "
                        "exclusive devices "
                        f"({sorted(set(names) - set(over))}); split "
                        "them into separate requests"
                    )
                touched_chips |= chips
                sharing_edits = sharing_edits.merge(
                    self._apply_oversubscription(
                        claim, request, cfgs[request], sorted(chips),
                        over,
                    )
                )
                continue
            sharing = getattr(cfgs[request], "sharing", None)
            if sharing is None:
                continue
            touched_chips |= chips
            sharing_edits = sharing_edits.merge(
                self._apply_sharing(
                    claim, request, sharing, sorted(chips), names
                )
            )

        common = self._cdi.common_edits(self.host)
        common.env.append(
            "TPU_VISIBLE_DEVICES=" + ",".join(str(i) for i in sorted(claim_chips))
        )
        common = common.merge(sharing_edits)
        with timer.segment("gen_write_cdi_spec"):
            cdi_ids = self._cdi.create_claim_spec_file(
                claim.uid, device_edits, common
            )
        by_name = dict(zip(sorted(device_edits), cdi_ids))
        for dev in prepared:
            dev.cdi_device_ids = [
                by_name[cdi_name_of[dev.canonical_name]]]
        return prepared

    def _check_config_kind(self, dev: AllocatableDevice, cfg) -> None:
        if dev.kind == DeviceKind.CHIP and not isinstance(
            cfg, api_configs.TpuConfig
        ):
            raise PrepareError(
                f"config kind {type(cfg).__name__} cannot apply to a chip"
            )
        if dev.kind in (DeviceKind.SUBSLICE_DYNAMIC,
                        DeviceKind.SUBSLICE_STATIC, DeviceKind.PARTITION) \
                and not isinstance(cfg, api_configs.SubSliceConfig):
            raise PrepareError(
                f"config kind {type(cfg).__name__} cannot apply to a sub-slice"
            )
        if dev.kind == DeviceKind.PASSTHROUGH and not isinstance(
            cfg, api_configs.PassthroughConfig
        ):
            raise PrepareError(
                f"config kind {type(cfg).__name__} cannot apply to a "
                "passthrough device"
            )

    def _apply_oversubscription(
        self,
        claim: ResourceClaim,
        request: str,
        cfg,
        chip_indices: list[int],
        device_names: list[str],
    ) -> ContainerEdits:
        """Sharing contract for oversubscribed partition tenants: the
        chips' cooperative time-slice policy (holder-counted across the
        co-tenant claims) plus a per-tenant tenancy dir whose HBM
        ceiling is the partition's per-slot budget -- "N tenant claims
        share one carve-out under TimeSlicingManager /
        MultiTenancyManager"."""
        gates = self._config.feature_gates
        if not gates.is_enabled(TIME_SLICING_SETTINGS) or \
                not gates.is_enabled(MULTI_TENANCY_SUPPORT):
            raise PrepareError(
                "oversubscribed partitions need the TimeSlicingSettings "
                "and MultiTenancySupport feature gates"
            )
        sharing = getattr(cfg, "sharing", None)
        ts_cfg = api_configs.TimeSlicingConfig()
        if sharing is not None and sharing.is_time_slicing and \
                sharing.time_slicing is not None:
            ts_cfg = sharing.time_slicing
        edits = self._timeslicing.set_time_slice(
            claim.uid, chip_indices, ts_cfg)
        tenant_hbm = min(
            self.allocatable[name].partition.tenant_hbm_bytes
            for name in device_names
        )
        mt_cfg = api_configs.MultiTenancyConfig(
            hbm_limit=str(tenant_hbm))
        mt_cfg.normalize()
        return edits.merge(self._tenancy.start(
            claim.uid, request, chip_indices, mt_cfg, device_names))

    def _apply_sharing(
        self,
        claim: ResourceClaim,
        request: str,
        sharing: api_configs.Sharing,
        chip_indices: list[int],
        device_names: list[str],
    ) -> ContainerEdits:
        gates = self._config.feature_gates
        if sharing.is_time_slicing:
            if sharing.time_slicing.interval != "Default" and not gates.is_enabled(
                TIME_SLICING_SETTINGS
            ):
                raise PrepareError(
                    "TimeSlicingSettings feature gate disabled"
                )
            return self._timeslicing.set_time_slice(
                claim.uid, chip_indices, sharing.time_slicing
            )
        if sharing.is_multi_tenancy:
            if not gates.is_enabled(MULTI_TENANCY_SUPPORT):
                raise PrepareError("MultiTenancySupport feature gate disabled")
            return self._tenancy.start(
                claim.uid, request, chip_indices, sharing.multi_tenancy,
                device_names,
            )
        return ContainerEdits()

    # -- unprepare ------------------------------------------------------------

    def unprepare(self, claim_uid: str) -> None:
        """Idempotent unprepare + cleanup (device_state.go:426).

        Mirrors prepare's locking: the global section only looks up the
        claim and marks it in flight; the teardown runs under the
        claim's chip shards so disjoint claims unprepare concurrently.
        Until the rollback's checkpoint removal commits, overlap
        validation still counts the claim's chips as held -- no one can
        grab a device mid-teardown."""
        timer = SegmentTimer("unprepare", claim_uid)
        try:
            t0 = time.monotonic()
            with self.pu_lock.acquire(timeout=10.0), self._lock:
                timer.segments["prep_lock_wait"] = time.monotonic() - t0
                cp = self._checkpoint.get()
                existing = cp.claims.get(claim_uid)
                if existing is None:
                    # Never prepared or already unprepared. Defensive spec
                    # delete (idempotent): this plugin's own two-phase flow
                    # can't leave a spec without a checkpoint entry, but an
                    # externally-manipulated/cross-version state root might.
                    # Same for the lease: a crash between the lease write
                    # and the reservation write orphans it.
                    self._cdi.delete_claim_spec_file(claim_uid)
                    self._leases.clear(claim_uid)
                    return
                if claim_uid in self._inflight:
                    raise PrepareError(
                        f"claim {claim_uid} prepare/unprepare in flight"
                    )
                if existing.state == ClaimState.PREPARE_STARTED.value:
                    owner = self._foreign_owner_alive(claim_uid)
                    if owner:
                        # A live peer process's prepare owns this
                        # claim's reservation (handover window):
                        # tearing it down now would race its device
                        # mutations. Retriable.
                        raise PrepareError(
                            f"claim {claim_uid} prepare in progress in "
                            f"plugin process {owner}; retry"
                        )
                # Shards first: a raise must not leave the uid stuck in
                # _inflight (see the same ordering in prepare()).
                shards = self._shards_of_checkpointed(existing)
                self._inflight.add(claim_uid)
            try:
                with self._shards.hold(shards, timer):
                    self._rollback(existing, timer=timer)
            finally:
                with self._lock:
                    self._inflight.discard(claim_uid)
        finally:
            self._record_segments(timer)
            timer.done()

    def _rollback(self, checkpointed: CheckpointedClaim,
                  timer: SegmentTimer | None = None) -> None:
        """Tear down whatever a claim holds: dynamic carve-outs, sharing
        state, CDI spec, checkpoint entry (unprepareDevices :898 +
        unpreparePartiallyPrepairedClaim :536). Reservation-only records
        (PrepareStarted, no live state) fall through every branch
        harmlessly -- holder-counted releases and rmtree are no-ops."""
        chip_indices: set[int] = set()
        for dev in checkpointed.devices:
            if dev.live and dev.live.get("vfio"):
                # Kernel boundary: return the function to the native
                # driver (vfio-device.go:189).
                self._vfio.unconfigure(dev.live["pciBdf"])
            elif dev.live and dev.live.get("partition"):
                # Holder-counted through the partition engine: the
                # carve-out dies only with its LAST tenant. Engine gone
                # (gate flipped off across a restart): derive the
                # holder count the same way the engine does -- another
                # claim record referencing the device means a co-tenant
                # workload may still be running on the carve-out.
                if self.partition_engine is not None:
                    self.partition_engine.detach(
                        checkpointed.uid, dev.canonical_name)
                else:
                    held_elsewhere = any(
                        other.uid != checkpointed.uid
                        and any(d.canonical_name == dev.canonical_name
                                for d in other.devices)
                        for other in self._checkpoint.get(
                            ).claims.values()
                    )
                    if not held_elsewhere:
                        self._registry.destroy(dev.live["uuid"])
            elif dev.live:
                self._registry.destroy(dev.live["uuid"])
            for core in self._cores_of(dev.canonical_name):
                pos = core // self.host.cores_per_chip
                if pos < len(self.host.chips):
                    # Sharing state is keyed by physical chip index.
                    chip_indices.add(self.host.chips[pos].index)
        # Holder-counted release: a chip shared with another claim (via
        # disjoint core-level carve-outs) keeps its policy file.
        self._timeslicing.release(checkpointed.uid, sorted(chip_indices))
        self._tenancy.stop(checkpointed.uid)
        self._cdi.delete_claim_spec_file(checkpointed.uid)
        self._checkpoint.update_claim(checkpointed.uid, None, timer=timer)
        self._leases.clear(checkpointed.uid)

    # -- introspection --------------------------------------------------------

    def prepared_claims(self) -> dict[str, CheckpointedClaim]:
        return self._checkpoint.get().claims

    def prepared_device_count(self) -> int:
        return sum(
            len(c.devices)
            for c in self._checkpoint.get().claims.values()
            if c.state == ClaimState.PREPARE_COMPLETED.value
        )
