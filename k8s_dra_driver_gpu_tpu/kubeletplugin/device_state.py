"""DeviceState: the node-side claim state machine.

Reference: cmd/gpu-kubelet-plugin/device_state.go (1328 LoC) -- idempotent
two-phase Prepare (PrepareStarted -> PrepareCompleted, :229-334), rollback
of partially prepared claims (:536), overlapping-allocation guard (:1212),
config precedence (class < claim, later wins; :1138), config dispatch to
sharing/sub-slice appliers (:1010), startup reconciliation of unknown
dynamic carve-outs (:388).

TPU specifics: a dynamic sub-slice "create" realizes the carve-out in the
node's live-sub-slice registry (the hardware-truth analog of the NVML MIG
walk -- TPU carve-outs are bounds handed to the runtime at container
start, so the registry is what crash recovery reconciles against) and
hands out a UUID; whole chips and core-level splits inject /dev/accel*
device nodes plus the TPU_* env contract via CDI.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid as uuidlib
from dataclasses import dataclass, field

from ..api import configs as api_configs
from ..api.decode import strict_decode
from ..pkg.featuregates import (
    DYNAMIC_SUB_SLICE,
    MULTI_TENANCY_SUPPORT,
    PASSTHROUGH_SUPPORT,
    TIME_SLICING_SETTINGS,
    FeatureGates,
)
from ..pkg.flock import Flock
from ..pkg.timing import SegmentTimer
from ..tpulib.binding import EnumerateOptions, TpuHostInfo, load as load_tpulib
from .cdi import CDIHandler, ContainerEdits
from .checkpoint import (
    CheckpointedClaim,
    CheckpointedDevice,
    CheckpointManager,
    ClaimState,
)
from .claim import ResourceClaim
from .deviceinfo import (
    AllocatableDevice,
    ChipInfo,
    DeviceKind,
    PassthroughInfo,
    SubSliceInfo,
)
from .vfio import VfioPciManager, VfioRegistry
from .sharing import MultiTenancyManager, TimeSlicingManager
from .subslice import (
    SubSliceLiveTuple,
    SubSliceSpecTuple,
    enumerate_subslice_devices,
)

logger = logging.getLogger(__name__)


class PrepareError(RuntimeError):
    pass


@dataclass
class Config:
    """Node plugin configuration."""

    root: str  # state root: checkpoint, CDI specs, policy files
    tpulib_opts: EnumerateOptions = field(default_factory=EnumerateOptions)
    feature_gates: FeatureGates = field(default_factory=FeatureGates)
    cdi_root: str | None = None
    boot_id: str | None = None
    # Run supervised per-claim tenancy agents (MPS-control-daemon analog).
    # Production default; mock configs default it off so unit tests don't
    # pay a child-process spawn per tenancy Prepare.
    tenancy_agents: bool = True
    # Admin-pre-carved static sub-slices (the static-MIG analog,
    # mig-parted style): canonical names like "ss-2x1x1-0" or
    # "chip-0-ss-1c-1". Published as-is; Prepare does not create (and
    # Unprepare does not destroy) a carve-out for them.
    static_subslices: tuple[str, ...] = ()

    @classmethod
    def mock(
        cls,
        root: str,
        topology: str = "v5e-4",
        worker_id: int = 0,
        gates: str = "DynamicSubSlice=true,TimeSlicingSettings=true,"
        "MultiTenancySupport=true",
        tenancy_agents: bool = False,
    ) -> "Config":
        return cls(
            root=root,
            tpulib_opts=EnumerateOptions(
                mock_topology=topology, worker_id=worker_id
            ),
            feature_gates=FeatureGates.parse(gates),
            cdi_root=os.path.join(root, "cdi"),
            tenancy_agents=tenancy_agents,
        )


class SubSliceRegistry:
    """Node-local registry of live dynamic carve-outs (hardware truth for
    crash reconciliation; the analog of walking NVML for stray MIG
    devices, nvlib.go:420)."""

    def __init__(self, root: str):
        self._path = os.path.join(root, "subslices.json")

    def list(self) -> dict[str, dict]:
        try:
            with open(self._path, encoding="utf-8") as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return {}

    def _write(self, entries: dict[str, dict]) -> None:
        # fsync: this registry is the crash-reconciliation source of
        # truth, so it gets the same durability as the checkpoint.
        tmp = self._path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(entries, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path)

    def create(self, live: SubSliceLiveTuple) -> None:
        entries = self.list()
        entries[live.uuid] = live.to_dict()
        self._write(entries)

    def destroy(self, uuid: str) -> None:
        entries = self.list()
        if entries.pop(uuid, None) is not None:
            self._write(entries)


class DeviceState:
    """Prepare/Unprepare engine over this host's allocatable devices."""

    def __init__(self, config: Config):
        self._config = config
        os.makedirs(config.root, exist_ok=True)
        self._lock = threading.Lock()
        # Node-global prepare/unprepare flock: excludes other plugin
        # processes across upgrades (reference driver.go:46-47).
        self.pu_lock = Flock(os.path.join(config.root, "pu.lock"))

        self._tpulib = load_tpulib()
        self.host: TpuHostInfo = self._tpulib.enumerate(config.tpulib_opts)
        self._profiles = self._tpulib.subslice_profiles(config.tpulib_opts)
        # Grid position of each physical chip (position == index on a
        # healthy host; they diverge when a chip is missing).
        self._pos_by_index = {
            chip.index: pos for pos, chip in enumerate(self.host.chips)
        }

        self._vfio = VfioPciManager(
            sys_root=config.tpulib_opts.sys_root or "/sys",
            dev_root=config.tpulib_opts.dev_root or "/dev",
            registry=VfioRegistry(config.root),
        )
        self.allocatable = self._enumerate_allocatable()
        self._checkpoint = CheckpointManager(config.root, boot_id=config.boot_id)
        self._registry = SubSliceRegistry(config.root)
        self._cdi = CDIHandler(
            cdi_root=config.cdi_root or os.path.join(config.root, "cdi")
        )
        self._timeslicing = TimeSlicingManager(config.root)
        self._tenancy = MultiTenancyManager(
            config.root,
            hbm_capacity_bytes=self.host.hbm_bytes_per_chip,
            spawn_agents=config.tenancy_agents,
        )

        if self._checkpoint.invalidated_on_boot:
            # A reboot destroyed all device state: the claim records are
            # gone, so the per-claim side state under the same persistent
            # root (sharing policies, tenancy dirs, CDI specs, live
            # carve-outs) must go with them or holder entries leak.
            self._cleanup_all_side_state()
        self.destroy_unknown_subslices()
        # Re-own tenancy state for claims that survived the restart
        # (respawn their enforcement agents; drop orphan dirs).
        self._tenancy.reconcile({
            uid for uid, c in self._checkpoint.get().claims.items()
            if c.state == ClaimState.PREPARE_COMPLETED.value
        })

    def stop(self) -> None:
        """Stop background machinery (supervised tenancy agents)."""
        self._tenancy.shutdown()

    def tenancy_agent_count(self) -> int:
        return self._tenancy.agent_count()

    # -- enumeration ----------------------------------------------------------

    def _enumerate_allocatable(self) -> dict[str, AllocatableDevice]:
        out: dict[str, AllocatableDevice] = {}
        for chip in self.host.chips:
            info = ChipInfo(chip=chip, host=self.host)
            out[info.canonical_name] = AllocatableDevice(
                kind=DeviceKind.CHIP, chip=info
            )
        expected = min(self.host.num_slice_chips, self.host.chips_per_host)
        degraded = len(self.host.chips) < expected
        if degraded:
            # A host missing chips keeps publishing the survivors as
            # whole chips (taints mark the gap) but offers no carve-outs:
            # the placement grid can't be trusted against a hole.
            logger.warning(
                "degraded host (%d/%d chips): not publishing sub-slices",
                len(self.host.chips), expected,
            )
        if self._config.feature_gates.is_enabled(PASSTHROUGH_SUPPORT):
            for chip in self.host.chips:
                group = self._vfio.iommu_group(chip.pci_bdf)
                if group < 0:
                    # No IOMMU group: the device could never be prepared
                    # for passthrough, so don't let a scheduler pick it.
                    logger.warning(
                        "chip %s has no iommu group: not publishing a "
                        "passthrough device", chip.pci_bdf,
                    )
                    continue
                info = PassthroughInfo(
                    chip=chip, host=self.host, iommu_group=group,
                )
                out[info.canonical_name] = AllocatableDevice(
                    kind=DeviceKind.PASSTHROUGH, passthrough=info
                )
        all_specs = (
            enumerate_subslice_devices(self.host, self._profiles)
            if not degraded else []
        )
        if self._config.feature_gates.is_enabled(DYNAMIC_SUB_SLICE):
            for spec in all_specs:
                # Full-host carve-outs duplicate the chip set; still
                # published (schedulers pick by shape), reference
                # publishes the full-GPU MIG profile too.
                info = SubSliceInfo(spec=spec, host=self.host, dynamic=True)
                out[info.canonical_name] = AllocatableDevice(
                    kind=DeviceKind.SUBSLICE_DYNAMIC, subslice=info
                )
        if self._config.static_subslices:
            if degraded:
                # Like the dynamic path: a host missing chips cannot
                # trust the placement grid -- keep the surviving whole
                # chips published and warn, never crash-loop the plugin
                # over a carve-out it can't honor right now.
                logger.warning(
                    "degraded host: not publishing static sub-slices %s",
                    list(self._config.static_subslices),
                )
            else:
                valid = {s.canonical_name() for s in all_specs}
                for name in self._config.static_subslices:
                    if name not in valid:
                        # A bad NAME is a config error on a healthy
                        # host: fail startup loudly rather than
                        # silently publishing less than declared.
                        raise ValueError(
                            f"static sub-slice {name!r} is not a valid "
                            f"carve-out for this host "
                            f"({self.host.accelerator_type or 'unknown'})"
                        )
                    spec = SubSliceSpecTuple.from_canonical_name(name)
                    info = SubSliceInfo(spec=spec, host=self.host,
                                        dynamic=False)
                    # Static wins over the identically-named dynamic
                    # device: the admin carved it; it must not be torn
                    # down at Unprepare.
                    out[info.canonical_name] = AllocatableDevice(
                        kind=DeviceKind.SUBSLICE_STATIC, subslice=info
                    )
        return out

    def _cleanup_all_side_state(self) -> None:
        import shutil  # noqa: PLC0415

        for sub in ("timeslice", "tenancy"):
            shutil.rmtree(os.path.join(self._config.root, sub),
                          ignore_errors=True)
        os.makedirs(os.path.join(self._config.root, "timeslice"), exist_ok=True)
        os.makedirs(os.path.join(self._config.root, "tenancy"), exist_ok=True)
        cdi_root = self._config.cdi_root or os.path.join(self._config.root, "cdi")
        if os.path.isdir(cdi_root):
            for name in os.listdir(cdi_root):
                if name.startswith("k8s.tpu.dra.dev-claim_"):
                    try:
                        os.unlink(os.path.join(cdi_root, name))
                    except OSError:
                        pass
        # Live carve-outs all belonged to pre-reboot claims.
        for live_uuid in list(self._registry.list()):
            self._registry.destroy(live_uuid)
        logger.warning("boot-ID change: cleared all per-claim side state")

    # -- crash reconciliation -------------------------------------------------

    def destroy_unknown_subslices(self) -> int:
        """Tear down live carve-outs AND orphaned vfio rebinds not
        referenced by any checkpointed claim (checkpoint is source of
        truth; device_state.go:388)."""
        cp = self._checkpoint.get()
        referenced = {
            dev.live["uuid"]
            for c in cp.claims.values()
            for dev in c.devices
            if dev.live and "uuid" in dev.live  # vfio lives carry no uuid
        }
        destroyed = 0
        for uid in list(self._registry.list()):
            if uid not in referenced:
                self._registry.destroy(uid)
                destroyed += 1
        # Orphaned passthrough rebinds: a crash between configure() and
        # the completed checkpoint leaves the chip on vfio-pci with no
        # claim record; the vfio registry lets us rebind it back.
        claimed_bdfs = {
            dev.live["pciBdf"]
            for c in cp.claims.values()
            for dev in c.devices
            if dev.live and dev.live.get("vfio")
        }
        if self._vfio.registry is not None:
            for bdf in list(self._vfio.registry.list()):
                if bdf not in claimed_bdfs:
                    logger.warning("unbinding orphaned vfio rebind of %s", bdf)
                    self._vfio.unconfigure(bdf)
                    destroyed += 1
        if destroyed:
            logger.warning(
                "reconciled %d unknown sub-slice(s)/rebind(s)", destroyed
            )
        return destroyed

    # -- prepare --------------------------------------------------------------

    def prepare(self, claim: ResourceClaim) -> list[str]:
        """Idempotent two-phase prepare; returns CDI device IDs.

        Holds the node-global flock for the whole operation so a second
        plugin process (upgrade handover) can't interleave its own
        prepare/unprepare between our overlap validation and checkpoint
        writes (reference driver.go:381, pulock.Acquire with 10s timeout).

        Per-segment wall times are logged at debug level (the t_prep_*
        instrumentation, reference driver.go:394-404).
        """
        timer = SegmentTimer("prepare", claim.uid)
        try:
            t0 = time.monotonic()
            # Keep acquisition inside the with-statement: pulling the
            # guard out would open an async-exception window where the
            # non-reentrant flock leaks held.
            with self.pu_lock.acquire(timeout=10.0), self._lock:
                timer.segments["prep_lock_acq"] = time.monotonic() - t0
                with timer.segment("prep_get_checkpoint"):
                    cp = self._checkpoint.get()
                existing = cp.claims.get(claim.uid)
                if (existing
                        and existing.state == ClaimState.PREPARE_COMPLETED.value):
                    # Idempotent return ONLY if the (un-fsync'd,
                    # regenerable) CDI spec actually survived; a
                    # crash-truncated spec falls through to a full
                    # re-prepare.
                    try:
                        spec_ok = self._cdi.read_spec(claim.uid) is not None
                    except ValueError:
                        spec_ok = False  # corrupt JSON
                    if spec_ok:
                        return [
                            i for d in existing.devices
                            for i in d.cdi_device_ids
                        ]
                    # Regenerating via rollback+re-prepare is only safe
                    # when it can't disturb state a RUNNING workload may
                    # hold: vfio rebinds and tenancy rendezvous dirs
                    # must not be torn down under a live pod.
                    disruptive = any(
                        d.live and d.live.get("vfio")
                        for d in existing.devices
                    ) or self._tenancy.active(claim.uid)
                    if disruptive:
                        logger.error(
                            "claim %s completed but CDI spec missing/"
                            "corrupt; NOT re-preparing (live vfio/"
                            "tenancy state) -- unprepare to recover",
                            claim.uid,
                        )
                        return [
                            i for d in existing.devices
                            for i in d.cdi_device_ids
                        ]
                    logger.warning(
                        "claim %s completed but CDI spec missing/corrupt; "
                        "re-preparing", claim.uid,
                    )
                    with timer.segment("prep_rollback_stale"):
                        self._rollback(existing)
                if (existing
                        and existing.state == ClaimState.PREPARE_STARTED.value):
                    # A previous Prepare died mid-flight: roll back its
                    # partial state, then retry fresh (device_state.go:277).
                    with timer.segment("prep_rollback_stale"):
                        self._rollback(existing)

                self._validate_no_overlap(cp, claim)

                # Resolve + validate configs BEFORE the PrepareStarted
                # write: a claim with a bad config now fails without
                # ever touching the checkpoint (no write+rollback pair).
                cfgs = self._resolve_configs(claim)

                with timer.segment("checkpoint_write_started"):
                    self._checkpoint.update(
                        lambda c: c.claims.__setitem__(
                            claim.uid,
                            CheckpointedClaim(
                                uid=claim.uid,
                                namespace=claim.namespace,
                                name=claim.name,
                                state=ClaimState.PREPARE_STARTED.value,
                            ),
                        )
                    )

                try:
                    with timer.segment("prep_devices"):
                        prepared = self._prepare_devices(claim, timer, cfgs)
                except BaseException:
                    # _prepare_devices rolled back its own partial device
                    # state; drop the PrepareStarted checkpoint entry.
                    self._checkpoint.update(
                        lambda c: c.claims.pop(claim.uid, None)
                    )
                    raise

                def complete(c):
                    c.claims[claim.uid] = CheckpointedClaim(
                        uid=claim.uid,
                        namespace=claim.namespace,
                        name=claim.name,
                        state=ClaimState.PREPARE_COMPLETED.value,
                        devices=prepared,
                    )

                with timer.segment("checkpoint_write_completed"):
                    self._checkpoint.update(complete)
                return [i for d in prepared for i in d.cdi_device_ids]
        finally:
            # Failed/slow/idempotent prepares need the breakdown most.
            timer.done()

    def _validate_no_overlap(self, cp, claim: ResourceClaim) -> None:
        """Reject preparing a device whose chips/cores another claim holds
        (guards scheduler races; device_state.go:1212-1249)."""
        held: dict[int, str] = {}  # core index -> claim uid
        for other in cp.claims.values():
            if other.uid == claim.uid:
                continue
            for dev in other.devices:
                for core in self._cores_of(dev.canonical_name):
                    held[core] = other.uid
        # Claims in PrepareStarted with no devices yet can't conflict.
        for result in claim.results:
            for core in self._cores_of(result.device):
                if core in held:
                    raise PrepareError(
                        f"device {result.device} overlaps with prepared "
                        f"claim {held[core]}"
                    )

    def _cores_of(self, canonical_name: str) -> tuple[int, ...]:
        """Position-based core set of a device (for overlap math).

        Uses grid POSITIONS, not raw accel indices, so whole-chip and
        carve-out claims account against the same coordinate system even
        when device indices are sparse."""
        dev = self.allocatable.get(canonical_name)
        if dev is None:
            return ()
        if dev.kind == DeviceKind.CHIP or dev.kind == DeviceKind.PASSTHROUGH:
            chip = (dev.chip or dev.passthrough).chip
            pos = self._pos_by_index[chip.index]
            return tuple(
                pos * self.host.cores_per_chip + k
                for k in range(self.host.cores_per_chip)
            )
        if dev.subslice is not None:
            return dev.subslice.spec.core_indices(self.host)
        return ()

    def _chips_at(self, positions: tuple[int, ...]):
        """Physical chips backing grid positions (PrepareError when a
        position has no live chip)."""
        chips = []
        for pos in positions:
            if pos >= len(self.host.chips):
                raise PrepareError(
                    f"grid position {pos} has no backing chip on this host"
                )
            chips.append(self.host.chips[pos])
        return chips

    def _resolve_configs(self, claim: ResourceClaim):
        """Per-request effective config: class-sourced first, claim-sourced
        later, later wins (GetOpaqueDeviceConfigs precedence :1138; a
        default TpuConfig/SubSliceConfig is injected when nothing matches
        :698-724). Resolved once per unique request."""
        ordered = [c for c in claim.configs if c.source == "FromClass"] + [
            c for c in claim.configs if c.source != "FromClass"
        ]
        first_device: dict[str, str] = {}
        for result in claim.results:
            first_device.setdefault(result.request, result.device)
        per_request: dict[str, object] = {}
        for request, device in first_device.items():
            winner = None
            for oc in ordered:
                if oc.applies_to(request):
                    winner = oc
            if winner is not None:
                cfg_obj = strict_decode(winner.parameters)
            else:
                dev = self.allocatable.get(device)
                if dev is not None and dev.kind in (
                    DeviceKind.SUBSLICE_DYNAMIC,
                    DeviceKind.SUBSLICE_STATIC,
                ):
                    cfg_obj = api_configs.SubSliceConfig()
                elif dev is not None and dev.kind == DeviceKind.PASSTHROUGH:
                    cfg_obj = api_configs.PassthroughConfig()
                else:
                    cfg_obj = api_configs.TpuConfig()
            cfg_obj.normalize()
            cfg_obj.validate()
            per_request[request] = cfg_obj
        return per_request

    def _prepare_devices(
        self, claim: ResourceClaim, timer: SegmentTimer, cfgs=None
    ) -> list[CheckpointedDevice]:
        """All-or-nothing: any failure rolls back the partial device state
        created by this attempt (carve-outs, sharing state, CDI spec)
        before re-raising (unpreparePartiallyPrepairedClaim analog,
        device_state.go:536)."""
        created_live: list[str] = []
        configured_vfio: list[str] = []
        touched_chips: set[int] = set()
        try:
            return self._prepare_devices_inner(
                claim, created_live, configured_vfio, touched_chips, timer,
                cfgs,
            )
        except BaseException:
            for live_uuid in created_live:
                self._registry.destroy(live_uuid)
            for bdf in configured_vfio:
                self._vfio.unconfigure(bdf)
            self._timeslicing.release(claim.uid, sorted(touched_chips))
            self._tenancy.stop(claim.uid)
            self._cdi.delete_claim_spec_file(claim.uid)
            raise

    def _prepare_devices_inner(
        self,
        claim: ResourceClaim,
        created_live: list[str],
        configured_vfio: list[str],
        touched_chips: set[int],
        timer: SegmentTimer,
        cfgs=None,
    ) -> list[CheckpointedDevice]:
        if cfgs is None:
            cfgs = self._resolve_configs(claim)
        prepared: list[CheckpointedDevice] = []
        device_edits: dict[str, ContainerEdits] = {}
        claim_chips: set[int] = set()
        # request -> (chips, device names) for one sharing application per
        # config group (the reference merges MPS edits per group,
        # cdi.go:181-307).
        groups: dict[str, tuple[set[int], list[str]]] = {}

        for result in claim.results:
            dev = self.allocatable.get(result.device)
            if dev is None:
                raise PrepareError(f"unknown device {result.device!r}")
            cfg = cfgs[result.request]
            self._check_config_kind(dev, cfg)

            edits = ContainerEdits()
            live = None
            if dev.kind == DeviceKind.CHIP:
                physical = [dev.chip.chip]
                edits.device_nodes.append(dev.chip.chip.devpath)
            elif dev.kind == DeviceKind.PASSTHROUGH:
                chip = dev.passthrough.chip
                physical = [chip]
                # Kernel boundary: rebind to vfio-pci (vfio-device.go:145).
                # Record BEFORE configuring: a failure mid-rebind must
                # still be rolled back (unconfigure is idempotent).
                configured_vfio.append(chip.pci_bdf)
                edits = edits.merge(
                    self._vfio.configure(chip.pci_bdf, cfg)
                )
                live = {"pciBdf": chip.pci_bdf, "vfio": True}
            else:
                ss = dev.subslice
                positions = (
                    ss.spec.chip_positions(self.host)
                    if not ss.spec.is_core_level
                    else (ss.spec.parent_chip,)
                )
                physical = self._chips_at(positions)
                for chip in physical:
                    edits.device_nodes.append(chip.devpath)
                if ss.spec.is_core_level:
                    edits.env.append(
                        f"TPU_CORE_BOUNDS={ss.spec.placement}"
                    )
                    edits.env.append("TPU_MEGACORE=disabled")
                else:
                    edits.env.append(
                        f"TPU_CHIPS_PER_HOST_BOUNDS={ss.spec.profile.replace('x', ',')}"
                    )
                if dev.kind == DeviceKind.SUBSLICE_DYNAMIC:
                    live_t = SubSliceLiveTuple(
                        spec=ss.spec, uuid=f"tpu-ss-{uuidlib.uuid4()}"
                    )
                    # HOT path analog of createMigDevice (nvlib.go:926).
                    with timer.segment("prep_create_subslice"):
                        self._registry.create(live_t)
                    created_live.append(live_t.uuid)
                    live = live_t.to_dict()

            physical_idxs = [c.index for c in physical]
            claim_chips.update(physical_idxs)
            grp = groups.setdefault(result.request, (set(), []))
            grp[0].update(physical_idxs)
            grp[1].append(result.device)

            # Additive per-chip markers: a pod consuming SEVERAL claims
            # gets every claim's CDI spec applied, and same-name env
            # (TPU_VISIBLE_DEVICES below) merges last-wins under CDI --
            # unique names merge as the union, so consumers can always
            # recover the full visible set (mock_workload_site does).
            for i in physical_idxs:
                edits.env.append(f"TPU_DEVICE_{i}=1")

            device_edits[result.device] = edits
            prepared.append(
                CheckpointedDevice(
                    canonical_name=result.device,
                    kind=dev.kind.value,
                    cdi_device_ids=[],
                    live=live,
                )
            )

        # One sharing application per request group over its full chip and
        # device set.
        sharing_edits = ContainerEdits()
        for request, (chips, names) in groups.items():
            sharing = getattr(cfgs[request], "sharing", None)
            if sharing is None:
                continue
            touched_chips |= chips
            sharing_edits = sharing_edits.merge(
                self._apply_sharing(
                    claim, request, sharing, sorted(chips), names
                )
            )

        common = self._cdi.common_edits(self.host)
        common.env.append(
            "TPU_VISIBLE_DEVICES=" + ",".join(str(i) for i in sorted(claim_chips))
        )
        common = common.merge(sharing_edits)
        with timer.segment("gen_write_cdi_spec"):
            cdi_ids = self._cdi.create_claim_spec_file(
                claim.uid, device_edits, common
            )
        by_name = dict(zip(sorted(device_edits), cdi_ids))
        for dev in prepared:
            dev.cdi_device_ids = [by_name[dev.canonical_name]]
        return prepared

    def _check_config_kind(self, dev: AllocatableDevice, cfg) -> None:
        if dev.kind == DeviceKind.CHIP and not isinstance(
            cfg, api_configs.TpuConfig
        ):
            raise PrepareError(
                f"config kind {type(cfg).__name__} cannot apply to a chip"
            )
        if dev.kind in (DeviceKind.SUBSLICE_DYNAMIC, DeviceKind.SUBSLICE_STATIC) \
                and not isinstance(cfg, api_configs.SubSliceConfig):
            raise PrepareError(
                f"config kind {type(cfg).__name__} cannot apply to a sub-slice"
            )
        if dev.kind == DeviceKind.PASSTHROUGH and not isinstance(
            cfg, api_configs.PassthroughConfig
        ):
            raise PrepareError(
                f"config kind {type(cfg).__name__} cannot apply to a "
                "passthrough device"
            )

    def _apply_sharing(
        self,
        claim: ResourceClaim,
        request: str,
        sharing: api_configs.Sharing,
        chip_indices: list[int],
        device_names: list[str],
    ) -> ContainerEdits:
        gates = self._config.feature_gates
        if sharing.is_time_slicing:
            if sharing.time_slicing.interval != "Default" and not gates.is_enabled(
                TIME_SLICING_SETTINGS
            ):
                raise PrepareError(
                    "TimeSlicingSettings feature gate disabled"
                )
            return self._timeslicing.set_time_slice(
                claim.uid, chip_indices, sharing.time_slicing
            )
        if sharing.is_multi_tenancy:
            if not gates.is_enabled(MULTI_TENANCY_SUPPORT):
                raise PrepareError("MultiTenancySupport feature gate disabled")
            return self._tenancy.start(
                claim.uid, request, chip_indices, sharing.multi_tenancy,
                device_names,
            )
        return ContainerEdits()

    # -- unprepare ------------------------------------------------------------

    def unprepare(self, claim_uid: str) -> None:
        """Idempotent unprepare + cleanup (device_state.go:426)."""
        with self.pu_lock.acquire(timeout=10.0), self._lock:
            cp = self._checkpoint.get()
            existing = cp.claims.get(claim_uid)
            if existing is None:
                # Never prepared or already unprepared. Defensive spec
                # delete (idempotent): this plugin's own two-phase flow
                # can't leave a spec without a checkpoint entry, but an
                # externally-manipulated/cross-version state root might.
                self._cdi.delete_claim_spec_file(claim_uid)
                return
            self._rollback(existing)

    def _rollback(self, checkpointed: CheckpointedClaim) -> None:
        """Tear down whatever a claim holds: dynamic carve-outs, sharing
        state, CDI spec, checkpoint entry (unprepareDevices :898 +
        unpreparePartiallyPrepairedClaim :536)."""
        chip_indices: set[int] = set()
        for dev in checkpointed.devices:
            if dev.live and dev.live.get("vfio"):
                # Kernel boundary: return the function to the native
                # driver (vfio-device.go:189).
                self._vfio.unconfigure(dev.live["pciBdf"])
            elif dev.live:
                self._registry.destroy(dev.live["uuid"])
            for core in self._cores_of(dev.canonical_name):
                pos = core // self.host.cores_per_chip
                if pos < len(self.host.chips):
                    # Sharing state is keyed by physical chip index.
                    chip_indices.add(self.host.chips[pos].index)
        # Holder-counted release: a chip shared with another claim (via
        # disjoint core-level carve-outs) keeps its policy file.
        self._timeslicing.release(checkpointed.uid, sorted(chip_indices))
        self._tenancy.stop(checkpointed.uid)
        self._cdi.delete_claim_spec_file(checkpointed.uid)
        self._checkpoint.update(
            lambda c: c.claims.pop(checkpointed.uid, None)
        )

    # -- introspection --------------------------------------------------------

    def prepared_claims(self) -> dict[str, CheckpointedClaim]:
        return self._checkpoint.get().claims

    def prepared_device_count(self) -> int:
        return sum(
            len(c.devices)
            for c in self._checkpoint.get().claims.values()
            if c.state == ClaimState.PREPARE_COMPLETED.value
        )
