"""Per-claim tenancy control agent: the MPS-control-daemon analog.

Reference: cmd/gpu-kubelet-plugin/sharing.go:214-379 -- the reference
runs an actual MPS control daemon per MultiTenancy claim (Deployment,
tmpfs shm, EXCLUSIVE_PROCESS, readiness asserted before Prepare
returns). TPU has no MPS daemon, but the enforcement role is the same:
a supervised per-claim agent OWNS the tenancy rendezvous dir and is the
single admission point for co-tenants -- a tenant that would exceed the
claim's max-client count or the chips' HBM capacity is DENIED, which
(via the CDI-injected preflight hook, tenancy_preflight.py) fails the
container start.

Protocol (unix socket `agent.sock` inside the tenancy dir, one
newline-terminated request per connection, mirrors rendezvous.py):

  STATUS                          -> "READY"
  REGISTER <client> <hbm_bytes>   -> "OK <granted>" | "DENIED <reason>"
  RELEASE <client>                -> "OK released"
  MEMBERS                         -> JSON {clients: {id: hbm}, ...}

Admissions are persisted to clients.json (atomic replace) so an agent
restart -- the plugin supervises it with the same watchdog pattern as
the CD coordination service -- keeps enforcing prior grants.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import socket
import socketserver
import sys
import threading

logger = logging.getLogger(__name__)

SOCKET_NAME = "agent.sock"
MANIFEST_NAME = "tenancy.json"
CLIENTS_NAME = "clients.json"
# Tombstone dir: a poststop hook that cannot reach the agent (e.g. the
# plugin was mid-restart) records the released client id here; the
# agent applies tombstones at startup and before each admission, so a
# lost RELEASE can never leak an admission slot permanently.
RELEASED_DIR = "released.d"


class TenancyState:
    """Manifest-driven admission control with persisted grants."""

    def __init__(self, tenancy_dir: str):
        self.dir = tenancy_dir
        self._lock = threading.Lock()
        self.manifest: dict = {}
        self.clients: dict[str, int] = {}
        self.reload()
        self._load_clients()
        self._apply_tombstones_locked()

    def reload(self) -> None:
        with open(os.path.join(self.dir, MANIFEST_NAME),
                  encoding="utf-8") as f:
            self.manifest = json.load(f)

    def _load_clients(self) -> None:
        try:
            with open(os.path.join(self.dir, CLIENTS_NAME),
                      encoding="utf-8") as f:
                self.clients = {
                    k: int(v) for k, v in json.load(f).items()
                }
        except (OSError, ValueError):
            self.clients = {}

    def _save_clients(self) -> None:
        from ..pkg.fsutil import write_json_atomic  # noqa: PLC0415

        write_json_atomic(os.path.join(self.dir, CLIENTS_NAME), self.clients)

    def _apply_tombstones_locked(self) -> None:
        """Release clients recorded in released.d by hooks that could
        not reach a live agent. Caller need not hold the lock at init;
        register() calls this under its lock."""
        rd = os.path.join(self.dir, RELEASED_DIR)
        try:
            names = os.listdir(rd)
        except FileNotFoundError:
            return
        changed = False
        for name in names:
            if self.clients.pop(name, None) is not None:
                changed = True
            try:
                os.unlink(os.path.join(rd, name))
            except OSError:
                pass
        if changed:
            self._save_clients()

    # -- admission ------------------------------------------------------------

    def register(self, client: str, hbm_bytes: int) -> tuple[bool, str]:
        with self._lock:
            self._apply_tombstones_locked()
            max_clients = self.manifest.get("maxClients")
            capacity = self.manifest.get("hbmCapacityBytes")
            others = {k: v for k, v in self.clients.items() if k != client}
            if max_clients is not None and len(others) + 1 > int(max_clients):
                return False, f"max clients ({max_clients}) reached"
            if capacity is not None and hbm_bytes + sum(others.values()) > int(
                capacity
            ):
                return (
                    False,
                    f"HBM budget exceeded: {hbm_bytes} requested, "
                    f"{int(capacity) - sum(others.values())} available",
                )
            self.clients[client] = hbm_bytes
            self._save_clients()
            return True, str(hbm_bytes)

    def release(self, client: str) -> None:
        with self._lock:
            if self.clients.pop(client, None) is not None:
                self._save_clients()

    def members(self) -> dict:
        with self._lock:
            return {
                "clients": dict(self.clients),
                "maxClients": self.manifest.get("maxClients"),
                "hbmCapacityBytes": self.manifest.get("hbmCapacityBytes"),
            }


def _handle_line(state: TenancyState, line: str) -> str:
    parts = line.strip().split()
    if not parts:
        return "ERROR empty request"
    cmd = parts[0].upper()
    if cmd == "STATUS":
        return "READY"
    if cmd == "MEMBERS":
        return json.dumps(state.members())
    if cmd == "REGISTER":
        if len(parts) < 3:
            return "ERROR usage: REGISTER <client> <hbm_bytes>"
        if "/" in parts[1] or parts[1] in (".", ".."):
            return "ERROR invalid client id"
        try:
            hbm = int(parts[2])
        except ValueError:
            return "ERROR hbm_bytes must be an integer"
        ok, detail = state.register(parts[1], hbm)
        return f"OK {detail}" if ok else f"DENIED {detail}"
    if cmd == "RELEASE":
        if len(parts) < 2:
            return "ERROR usage: RELEASE <client>"
        state.release(parts[1])
        return "OK released"
    return f"ERROR unknown command {cmd}"


def serve(tenancy_dir: str) -> int:
    state = TenancyState(tenancy_dir)
    sock_path = os.path.join(tenancy_dir, SOCKET_NAME)
    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            line = self.rfile.readline().decode(errors="replace")
            self.wfile.write((_handle_line(state, line) + "\n").encode())

    class Server(socketserver.ThreadingUnixStreamServer):
        daemon_threads = True

    server = Server(sock_path, Handler)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGUSR1, lambda *a: state.reload())
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    logger.info("tenancy agent serving on %s", sock_path)
    stop.wait()
    server.shutdown()
    server.server_close()
    return 0


def query(tenancy_dir: str, request: str, timeout: float = 2.0) -> str:
    """Client helper (plugin readiness checks + preflight hook)."""
    sock_path = os.path.join(tenancy_dir, SOCKET_NAME)
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(timeout)
        s.connect(sock_path)
        s.sendall((request + "\n").encode())
        chunks = []
        while True:
            b = s.recv(4096)
            if not b:
                break
            chunks.append(b)
            if b.endswith(b"\n"):
                break
    return b"".join(chunks).decode().strip()


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="tpu-tenancy-agent")
    p.add_argument("--dir", required=True, help="tenancy dir (owns it)")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    return serve(args.dir)


if __name__ == "__main__":
    sys.exit(main())
