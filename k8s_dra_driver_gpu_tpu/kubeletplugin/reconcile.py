"""Node-side cross-layer reconciliation sweep (permanent-failure
recovery, the plugin half of pkg/recovery.py).

Four layers describe the same claims on a node and MUST agree:

  1. the durable checkpoint (kubeletplugin/checkpoint.py),
  2. the live kube API (the claims the scheduler believes exist),
  3. the transient CDI spec files (kubeletplugin/cdi.py),
  4. the hardware-truth artifacts: live sub-slice carve-outs,
     vfio rebinds, and reservation pid-leases.

Any single crash window (plugin death mid-prepare, a wiped state dir,
a controller eviction racing a node restart) can leave exactly one
layer ahead of or behind the others. The startup reconciliation
(DeviceState.destroy_unknown_subslices, boot-ID invalidation) repairs
what a RESTART can see; this sweep repairs the same divergences
PERIODICALLY on a live plugin, in both directions:

- artifacts whose claim is gone are destroyed (orphan carve-outs,
  CDI specs, leases, stale checkpoint records -- reusing the stale-
  claim GC), and
- claims whose DEVICES are gone (a chip that fell off the host) are
  re-declared failed on the kube API (PermanentFailure condition) so
  the eviction controller migrates them off the broken hardware.

The CD plugin gets the same treatment (``CDStateReconciler``): stale
CD claim records unprepare (dropping the daemon node label when the
last channel goes), and orphaned CD CDI specs unwind through
``CDDeviceState.unwind_failed_prepare`` -- which also reclaims the
node label of a ComputeDomain that no longer exists.

Everything exports ``tpu_dra_recovery_*`` metrics
(pkg/metrics.RecoveryMetrics): ``orphans_repaired_total`` by kind,
and ``reconcile_drift`` -- the per-sweep divergence count that should
read 0 on a healthy node.
"""

from __future__ import annotations

import logging
import os
import threading

from ..pkg import positive_float_env
from ..pkg.recovery import (
    allocation_nodes,
    set_permanent_failure_condition,
)
from .checkpoint import ClaimState
from .cleanup import DEFAULT_INTERVAL_S as _CLEANUP_INTERVAL_S
from .cleanup import lookup_claim

logger = logging.getLogger(__name__)

# The sweep subsumes the stale-claim GC (cleanup.py), so a tightened
# TPU_DRA_CLEANUP_INTERVAL_S tightens the whole sweep too.
SWEEP_INTERVAL_S = min(
    positive_float_env("TPU_DRA_RECOVERY_SWEEP_S", default=120.0,
                       floor=0.05),
    _CLEANUP_INTERVAL_S,
)


class NodeStateReconciler:
    """Periodic cross-layer audit for the chip kubelet plugin."""

    def __init__(self, device_state, kube, cleanup=None, metrics=None,
                 interval: float = SWEEP_INTERVAL_S,
                 node_name: str | None = None):
        self._state = device_state
        self._kube = kube
        self._cleanup = cleanup  # CheckpointCleanupManager | None
        self._metrics = metrics  # pkg.metrics.RecoveryMetrics | None
        self._interval = interval
        # This node's identity (== its ResourceSlice pool name): the
        # moved-claim sweep needs it to tell "re-placed elsewhere onto
        # a same-named device" from "still allocated here". None =
        # fall back to device-name matching only (direct-driven test
        # states with no node identity).
        self._node = node_name
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="recovery-sweep", daemon=True)
        self.last_sweep: dict = {}

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread.ident is not None:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.reconcile_once()
            except Exception:  # noqa: BLE001 - sweep must survive
                logger.exception("recovery sweep failed")

    # -- one sweep ------------------------------------------------------------

    def reconcile_once(self) -> dict:
        """One full audit; returns repaired/declared counts by kind.
        Order matters: stale checkpoint records are unprepared FIRST
        (their teardown removes the matching CDI spec / carve-out /
        lease through the normal pipeline), so the later orphan passes
        only see artifacts with genuinely no owning record. The live-
        claim lookups are computed ONCE and shared by the stale GC and
        both claim audits -- one GET per checkpointed claim per sweep,
        not three."""
        counts = {"stale_claim": 0, "moved_claim": 0, "cdi_spec": 0,
                  "carveout": 0, "lease": 0, "devices_gone": 0}
        lookups = {
            uid: lookup_claim(self._kube, uid, rec.namespace, rec.name)
            for uid, rec in self._state.prepared_claims().items()
        }
        if self._cleanup is not None:
            counts["stale_claim"] = len(
                self._cleanup.cleanup_once(lookups=lookups))
        counts["moved_claim"] = self._sweep_moved_claims(
            self._state.prepared_claims(), lookups)
        counts["cdi_spec"] = self._sweep_cdi_specs()
        counts["carveout"] = self._state.destroy_unknown_subslices()
        if self._state.partition_engine is not None:
            # Safety net for the holder-counted teardown: a partition
            # whose last tenant record was GC'd above (instead of
            # unprepared) is reaped here; devices a re-plan retired
            # leave the allocatable set once their records are gone.
            counts["idle_partition"] = \
                self._state.partition_engine.reap_idle()
            counts["idle_partition"] += \
                self._state.prune_retired_partitions()
        counts["lease"] = self._sweep_leases()
        counts["devices_gone"] = self._declare_gone_devices(
            self._state.prepared_claims(), lookups)
        self._observe(counts)
        if any(counts.values()):
            logger.warning("recovery sweep repaired/declared: %s",
                           {k: v for k, v in counts.items() if v})
        self.last_sweep = counts
        return counts

    def _lookup(self, lookups, uid, rec):
        hit = lookups.get(uid)
        if hit is None:
            hit = lookup_claim(self._kube, uid, rec.namespace, rec.name)
        return hit

    def _sweep_moved_claims(self, claims, lookups) -> int:
        """Completed records whose live claim no longer holds any of
        this NODE's checkpointed devices -- deallocated by the eviction
        controller, or re-placed onto another node. The plugin-side
        completion of a drain: unprepare through the normal pipeline
        (carve-outs destroyed, sharing released, CDI spec + record
        dropped) exactly as a kubelet unprepare would.

        Device names are node-local indices (chip-0 exists on every
        node), so name overlap alone cannot prove the claim is still
        ours: with a node identity configured, an allocation whose
        nodeSelector POSITIVELY pins another node drains too. An
        allocation with no node evidence at all is kept (fail-safe for
        externally authored claims)."""
        drained = 0
        for uid, claim in list(claims.items()):
            if claim.state != ClaimState.PREPARE_COMPLETED.value:
                continue
            status, obj = self._lookup(lookups, uid, claim)
            if status != "live":
                continue  # stale-claim GC owns gone; unknown = keep
            if self._still_local(obj, claim):
                continue
            try:
                self._state.unprepare(uid)
            except Exception:  # noqa: BLE001 - sweep must survive
                logger.exception("drain unprepare failed for moved "
                                 "claim %s", uid)
                continue
            drained += 1
            logger.warning(
                "unprepared moved claim %s (%s/%s): its allocation no "
                "longer references this node's devices", uid,
                claim.namespace, claim.name)
        return drained

    def _still_local(self, obj: dict, claim) -> bool:
        alloc = obj.get("status", {}).get("allocation") or {}
        results = alloc.get("devices", {}).get("results", [])
        held = {r.get("device", "") for r in results}
        mine = {d.canonical_name for d in claim.devices}
        if not held & mine:
            return False  # deallocated, or holding other devices
        if self._node is None:
            return True  # no node identity: name match is all we have
        nodes = allocation_nodes(obj)
        if nodes and self._node not in nodes:
            return False  # positively pinned to another node
        return True  # pinned here, or no node evidence: fail safe

    def _sweep_cdi_specs(self) -> int:
        """CDI specs whose claim has no checkpoint record. The record
        snapshot is taken AFTER the spec listing: a prepare commits its
        PrepareStarted reservation before it writes the spec, so any
        spec seen by the listing either has its record in the (later)
        snapshot or is a true orphan (e.g. a crash between a rollback's
        spec delete and its checkpoint commit, replayed in the other
        order). A stale pre-listing snapshot would miss a prepare that
        started mid-sweep and delete its LIVE spec."""
        uids = self._state._cdi.list_claim_uids()
        claims = self._state.prepared_claims()
        repaired = 0
        for uid in uids:
            if uid not in claims:
                self._state._cdi.delete_claim_spec_file(uid)
                repaired += 1
                logger.warning("destroyed orphan CDI spec for %s", uid)
        return repaired

    def _sweep_leases(self) -> int:
        """Reservation leases with no checkpoint record and no LIVE
        owner process. Runs under the node reservation flock: the
        lease-then-record write order in prepare() happens entirely
        inside that critical section, so holding it here means no
        in-flight reservation can be sliced between our two reads."""
        leases = self._state._leases
        try:
            names = os.listdir(leases._dir)
        except FileNotFoundError:
            return 0
        repaired = 0
        with self._state.pu_lock.acquire(timeout=10.0):
            claims = self._state.prepared_claims()
            for name in names:
                if not name.endswith(".json"):
                    continue
                uid = name[:-len(".json")]
                if uid in claims:
                    continue
                if self._state._foreign_owner_alive(uid):
                    continue  # a peer's reservation section, mid-write
                leases.clear(uid)
                repaired += 1
                logger.warning("cleared orphan reservation lease %s",
                               uid)
        return repaired

    def _declare_gone_devices(self, claims, lookups) -> int:
        """Claims whose checkpointed devices no longer exist on this
        host (a chip fell out of enumeration): re-declare failure ON
        THE CLAIM so the eviction controller migrates it -- the node
        cannot repair missing hardware, only report it honestly."""
        declared = 0
        allocatable = self._state.allocatable
        for uid, claim in claims.items():
            if claim.state != ClaimState.PREPARE_COMPLETED.value:
                continue
            gone = [d.canonical_name for d in claim.devices
                    if d.canonical_name not in allocatable]
            if not gone:
                continue
            status, obj = self._lookup(lookups, uid, claim)
            if status != "live":
                continue  # gone: stale GC's case; unknown: next sweep
            if set_permanent_failure_condition(
                    self._kube, obj, "True", "DevicesGone",
                    f"device(s) {sorted(gone)} no longer exist on this "
                    "host; claim needs migration"):
                declared += 1
                if self._metrics is not None:
                    self._metrics.permanent_failures.labels(
                        "sweep").inc()
                logger.error(
                    "claim %s references vanished device(s) %s: "
                    "declared PermanentFailure", uid, sorted(gone))
        return declared

    def _observe(self, counts: dict) -> None:
        if self._metrics is None:
            return
        for kind in ("stale_claim", "moved_claim", "cdi_spec",
                     "carveout", "lease", "idle_partition"):
            if counts.get(kind):
                self._metrics.orphans_repaired.labels(kind).inc(
                    counts[kind])
        for kind, n in counts.items():
            self._metrics.reconcile_drift.labels(kind).set(n)


class CDStateReconciler:
    """The same audit for the compute-domain plugin's (single-phase)
    state: stale claim records unprepare through the normal path, and
    orphaned CDI specs unwind via ``unwind_failed_prepare`` -- which
    also reclaims the daemon node label when the labeled ComputeDomain
    is positively gone (a dissolved gang must not pin daemon pods)."""

    def __init__(self, cd_state, kube, metrics=None):
        self._state = cd_state
        self._kube = kube
        self._metrics = metrics
        self.last_sweep: dict = {}

    def reconcile_once(self) -> dict:
        counts = {"cd_stale_claim": 0, "cd_cdi_spec": 0}
        claims = self._state.prepared_claims()
        for uid, rec in list(claims.items()):
            if not self._claim_gone(uid, rec):
                continue
            try:
                self._state.unprepare(uid)
            except Exception:  # noqa: BLE001 - sweep must survive
                logger.exception("stale CD claim unprepare failed "
                                 "for %s", uid)
                continue
            counts["cd_stale_claim"] += 1
            logger.warning("unprepared stale CD claim %s (%s/%s)",
                           uid, rec.namespace, rec.name)
        claims = self._state.prepared_claims()
        for uid in self._state._cdi.list_claim_uids():
            if uid in claims:
                continue
            self._state.unwind_failed_prepare(uid)
            counts["cd_cdi_spec"] += 1
            logger.warning("unwound orphan CD CDI spec for %s", uid)
        if self._metrics is not None:
            for kind, n in counts.items():
                if n:
                    self._metrics.orphans_repaired.labels(kind).inc(n)
                self._metrics.reconcile_drift.labels(kind).set(n)
        self.last_sweep = counts
        return counts

    def _claim_gone(self, uid: str, rec) -> bool:
        status, _ = lookup_claim(self._kube, uid, rec.namespace,
                                 rec.name)
        return status == "gone"
