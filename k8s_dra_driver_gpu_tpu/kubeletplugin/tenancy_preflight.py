"""Tenancy preflight: CDI createContainer hook enforcing admission.

Reference: the reference's MPS enforcement happens because workloads can
only reach the GPU through the MPS control daemon's pipe directory
(sharing.go:379). On TPU the enforcement point is container start: the
claim's CDI spec injects this program as a createContainer hook
(nvidia-cdi-hook analog, gpu main.go:293); the container runtime runs it
on the HOST with the OCI container state on stdin. It registers the
tenant with the claim's tenancy agent -- a tenant that would exceed the
claim's max-client count or HBM capacity gets DENIED, the hook exits
nonzero, and the runtime refuses to start the container.

Exit 0 = admitted. Exit 1 = denied or agent unreachable (fail closed:
an unreachable agent must not admit unlimited tenants).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .tenancy_agent import query


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="tpu-tenancy-preflight")
    p.add_argument("--dir", required=True, dest="tenancy_dir",
                   help="host path of the claim's tenancy dir")
    p.add_argument("--hbm-bytes", type=int, default=0,
                   help="this tenant's per-chip HBM budget")
    p.add_argument("--client-id", default="",
                   help="override client identity (default: OCI state id)")
    p.add_argument("--release", action="store_true",
                   help="poststop: free this tenant's admission slot")
    args = p.parse_args(argv)

    client = args.client_id
    if not client:
        # OCI hooks receive the container state JSON on stdin.
        try:
            state = json.load(sys.stdin)
            client = state.get("id", "")
        except (ValueError, OSError):
            client = ""
    if not client:
        print("tenancy-preflight: no client identity", file=sys.stderr)
        return 0 if args.release else 1

    if "/" in client or client in (".", ".."):
        print("tenancy-preflight: invalid client id", file=sys.stderr)
        return 0 if args.release else 1

    request = (f"RELEASE {client}" if args.release
               else f"REGISTER {client} {args.hbm_bytes}")
    try:
        answer = query(args.tenancy_dir, request)
    except OSError as e:
        print(f"tenancy-preflight: agent unreachable: {e}", file=sys.stderr)
        if args.release:
            # Leave a tombstone so the slot is reclaimed when the agent
            # is back (it applies released.d before each admission).
            # Only while the tenancy dir still exists: a poststop racing
            # Unprepare must not recreate the removed dir (a real dir
            # behind the sock symlink would dodge the dangling-symlink
            # sweep in reconcile() and leak).
            from .tenancy_agent import RELEASED_DIR  # noqa: PLC0415

            if os.path.isdir(args.tenancy_dir):
                try:
                    rd = os.path.join(args.tenancy_dir, RELEASED_DIR)
                    os.makedirs(rd, exist_ok=True)
                    with open(os.path.join(rd, client), "w"):
                        pass
                except OSError:
                    pass
            return 0  # never block container teardown
        return 1  # fail closed on admission
    if args.release or answer.startswith("OK"):
        return 0
    print(f"tenancy-preflight: {answer}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
