"""tpu-kubelet-plugin entry point.

Reference: cmd/gpu-kubelet-plugin/main.go -- urfave/cli app with env-var
mirrors for every flag (:80), metrics server (:269-276), plugin start
(:240). Flags mirror the reference's surface where meaningful on TPU.

Run (mock mode, no cluster):
    python -m k8s_dra_driver_gpu_tpu.kubeletplugin.main \
        --mock-topology v5e-4 --state-root /tmp/tpu-dra --standalone
"""

from __future__ import annotations

import argparse
import logging
import os

import sys

from .. import __version__
from ..pkg import logsetup
from ..pkg.debug import start_debug_signal_handlers, wait_for_termination
from ..pkg.featuregates import FeatureGates
from ..pkg.kubeclient import FakeKubeClient, KubeClient
from ..pkg.metrics import DRARequestMetrics, MetricsServer
from ..pkg.dra.service import PluginServer
from ..tpulib.binding import ENV_MOCK_HEALTH_EVENTS, EnumerateOptions
from . import DRIVER_NAME
from .device_state import Config
from .driver import Driver

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-kubelet-plugin",
        description="TPU DRA kubelet plugin (driver %s)" % DRIVER_NAME,
    )
    env = os.environ.get
    p.add_argument("--node-name", default=env("NODE_NAME", ""),
                   help="node this plugin serves [NODE_NAME]")
    p.add_argument("--state-root",
                   default=env("STATE_ROOT", "/var/lib/tpu-dra"),
                   help="checkpoint/policy state root [STATE_ROOT]")
    p.add_argument("--cdi-root", default=env("CDI_ROOT", "/var/run/cdi"),
                   help="CDI spec dir [CDI_ROOT]")
    p.add_argument("--plugin-dir",
                   default=env("PLUGIN_DIR",
                               "/var/lib/kubelet/plugins/tpu.dra.dev"),
                   help="DRA plugin socket dir [PLUGIN_DIR]")
    p.add_argument("--registry-dir",
                   default=env("REGISTRY_DIR",
                               "/var/lib/kubelet/plugins_registry"),
                   help="kubelet plugin-registry socket dir [REGISTRY_DIR]")
    p.add_argument("--metrics-port", type=int,
                   default=int(env("METRICS_PORT", "0")),
                   help="Prometheus port (0=disabled) [METRICS_PORT]")
    p.add_argument("--healthcheck-port", type=int,
                   default=int(env("HEALTHCHECK_PORT", "0")),
                   help="/healthz port probing own sockets (0=disabled) "
                        "[HEALTHCHECK_PORT]")
    p.add_argument("--feature-gates", default=env("FEATURE_GATES", ""),
                   help="Gate1=true,Gate2=false [FEATURE_GATES]")
    p.add_argument("--mock-topology", default=env("TPULIB_MOCK_TOPOLOGY"),
                   help="use mock tpulib with this topology "
                        "[TPULIB_MOCK_TOPOLOGY]")
    p.add_argument("--mock-worker-id", type=int,
                   default=int(env("TPULIB_MOCK_WORKER_ID", "0")),
                   help="mock worker id [TPULIB_MOCK_WORKER_ID]")
    p.add_argument("--sys-root", default=env("SYS_ROOT", ""),
                   help="sysfs root override (containerized plugins "
                        "mount the host's /sys here; also the fake-"
                        "PCI-tree seam for vfio tests) [SYS_ROOT]")
    p.add_argument("--dev-root", default=env("DEV_ROOT", ""),
                   help="devfs root override, like --sys-root "
                        "[DEV_ROOT]")
    p.add_argument("--publication-mode",
                   choices=["auto", "legacy", "combined", "split"],
                   default=env("PUBLICATION_MODE", "auto"),
                   help="ResourceSlice publication mode; auto sniffs the "
                        "server version (reference driver.go:190,574) "
                        "[PUBLICATION_MODE]")
    p.add_argument("--static-subslices",
                   default=env("STATIC_SUBSLICES", ""),
                   help="comma-separated admin-pre-carved sub-slices "
                        "(static-MIG analog), e.g. "
                        "'ss-2x1x1-0,chip-0-ss-1c-1' [STATIC_SUBSLICES]")
    p.add_argument("--partition-set",
                   default=env("TPU_DRA_PARTITION_SET", ""),
                   help="path to a PartitionSet JSON file (multi-tenant "
                        "partition engine, pkg/partition; needs the "
                        "TenantPartitioning feature gate) "
                        "[TPU_DRA_PARTITION_SET]")
    p.add_argument("--additional-health-kinds-to-ignore",
                   default=env("ADDITIONAL_HEALTH_KINDS_TO_IGNORE", ""),
                   help="comma-separated health kinds never tainted "
                        "[ADDITIONAL_HEALTH_KINDS_TO_IGNORE] (reference: "
                        "additional-xids-to-ignore)")
    p.add_argument("-v", "--verbosity", type=int,
                   default=int(env("V", "4")),
                   help="log verbosity: 0 errors, 4 info, 6+ debug "
                        "incl. t_prep_* segments [V]")
    p.add_argument("--standalone", action="store_true",
                   help="no API server: in-memory kube client (dev/mock)")
    p.add_argument("--kube-api", default=env("KUBE_API", ""),
                   help="API server URL override [KUBE_API]")
    p.add_argument("--version", action="version", version=__version__)
    return p


def run(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    logsetup.setup(args.verbosity)
    start_debug_signal_handlers()
    # Banner + structured startup-config dump: always visible, even at
    # verbosity 0 (logging contract, pkg/logsetup.py).
    logsetup.log_startup(__name__, "tpu-kubelet-plugin", __version__, args)

    gates = FeatureGates.parse(args.feature_gates)
    config = Config(
        root=args.state_root,
        cdi_root=args.cdi_root,
        feature_gates=gates,
        tpulib_opts=EnumerateOptions(
            mock_topology=args.mock_topology,
            worker_id=args.mock_worker_id if args.mock_topology else None,
            sys_root=args.sys_root or None,
            dev_root=args.dev_root or None,
            # Mock health injection (TPULIB_MOCK_HEALTH_EVENTS, incl.
            # the @control-file form) rides the same opts the health
            # monitor polls with -- the mock-NVML event-injection seam.
            health_events=os.environ.get(ENV_MOCK_HEALTH_EVENTS),
        ),
        static_subslices=tuple(
            s.strip() for s in args.static_subslices.split(",") if s.strip()
        ),
    )
    node_name = args.node_name or os.uname().nodename
    if args.partition_set:
        from ..pkg.featuregates import TENANT_PARTITIONING  # noqa: PLC0415
        from ..pkg.partition import PartitionSet  # noqa: PLC0415

        # Bad layout files fail startup loudly (PartitionSpecError),
        # like a bad --static-subslices name: never silently publish
        # less than the operator declared. Pool globs match against
        # this node's pool (node-local pools are named after the node).
        # Same contract for the gate: DeviceState only builds the
        # engine under TenantPartitioning, so a declared layout with
        # the gate off would silently publish nothing. (To drain a
        # node out of partitioning, drop the flag WITH the gate -- the
        # engine-gone unprepare path retires leftover carve-outs.)
        if not gates.is_enabled(TENANT_PARTITIONING):
            raise SystemExit(
                f"--partition-set {args.partition_set} requires the "
                f"{TENANT_PARTITIONING} feature gate (--feature-gates "
                f"{TENANT_PARTITIONING}=true)")
        config.partition_set = PartitionSet.from_file(args.partition_set)
        config.pool_name = node_name
    else:
        from ..pkg.featuregates import TENANT_PARTITIONING  # noqa: PLC0415

        if gates.is_enabled(TENANT_PARTITIONING):
            # No bootstrap file: the engine starts with an EMPTY
            # layout and the PartitionSet CRD watcher (pkg/autoscale,
            # wired in Driver) populates it from the cluster-scoped
            # object -- the serving autoscaler's managed path, where
            # the CRD is the source of truth and no node-local file
            # exists at all.
            from ..pkg.partition import PartitionSet  # noqa: PLC0415

            config.partition_set = PartitionSet.from_dict({})
            config.pool_name = node_name

    metrics = DRARequestMetrics()
    # Retry/breaker/quarantine + recovery-sweep counters share the
    # request-metrics registry so one /metrics endpoint carries the
    # whole story.
    from ..pkg.metrics import (  # noqa: PLC0415
        RecoveryMetrics,
        ResilienceMetrics,
        register_build_info,
    )

    register_build_info(metrics.registry, gates)
    from ..pkg.retry import RetryingKubeClient  # noqa: PLC0415

    resilience = ResilienceMetrics(registry=metrics.registry)
    recovery_metrics = RecoveryMetrics(registry=metrics.registry)
    kube = RetryingKubeClient(
        FakeKubeClient() if args.standalone else KubeClient(
            host=args.kube_api or None
        ),
        metrics=resilience,
    )
    ignored = tuple(
        k.strip()
        for k in args.additional_health_kinds_to_ignore.split(",")
        if k.strip()
    )
    driver = Driver(config, kube, node_name, metrics=metrics,
                    publication_mode=(None if args.publication_mode == "auto"
                                      else args.publication_mode),
                    additional_ignored_health_kinds=ignored,
                    resilience=resilience,
                    recovery_metrics=recovery_metrics)

    server = PluginServer(
        DRIVER_NAME,
        plugin_dir=args.plugin_dir,
        registry_dir=args.registry_dir,
        prepare_fn=driver.prepare_resource_claims,
        unprepare_fn=driver.unprepare_resource_claims,
    )

    extras = []
    if args.metrics_port > 0:
        m = MetricsServer(
            metrics.registry, host="0.0.0.0", port=args.metrics_port
        )
        m.start()
        extras.append(m)

    driver.start()
    server.start()
    if args.healthcheck_port > 0:
        from ..pkg.healthcheck import HealthcheckServer  # noqa: PLC0415

        h = HealthcheckServer(
            server.plugin_socket, server.registry_socket,
            host="0.0.0.0", port=args.healthcheck_port,
        )
        h.start()
        extras.append(h)
    logger.info(
        "serving DRA on %s (registry %s); %d allocatable device(s)",
        server.plugin_socket, server.registry_socket,
        len(driver.state.allocatable),
    )

    try:
        wait_for_termination()
    finally:
        server.stop()
        driver.stop()
        for e in extras:
            e.stop()
    return 0


if __name__ == "__main__":
    sys.exit(run())
