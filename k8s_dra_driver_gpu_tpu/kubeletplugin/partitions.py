"""KEP-4815 partitionable devices: shared counters over chips/cores/HBM.

Reference: cmd/gpu-kubelet-plugin/partitions.go -- per-GPU SharedCounters
(memory slices + per-capacity counters) with PartGetDevice/
PartSharedCounterSets/PartCapacities (:300-326); consumed by the
KEP-4815 "split"/"combined" ResourceSlice modes (driver.go:190).

TPU model: one counter set per host ("host-counters") tracking
per-TensorCore occupancy (the finest allocation grain) plus HBM bytes.
Every chip and every sub-slice carve-out consumes its core counters, so
the scheduler can never over-commit a core between a whole-chip claim
and a carve-out claim.
"""

from __future__ import annotations

from ..tpulib.binding import TpuHostInfo
from .deviceinfo import AllocatableDevice, DeviceKind

COUNTER_SET = "host-counters"


def shared_counter_sets(host: TpuHostInfo) -> list[dict]:
    """The counter sets block for a ResourceSlice (sharedCounters)."""
    counters: dict[str, dict] = {}
    for chip in host.chips:
        for core in range(host.cores_per_chip):
            counters[f"core-{chip.index}-{core}"] = {"value": "1"}
        counters[f"hbm-{chip.index}"] = {
            "value": str(host.hbm_bytes_per_chip)
        }
    return [{"name": COUNTER_SET, "counters": counters}]


def consumed_counters(
    dev: AllocatableDevice, host: TpuHostInfo
) -> list[dict]:
    """The consumesCounters block for one device.

    Partition devices (pkg/partition) consume PER-TENANT-SLOT shares:
    each core counter is debited ``1/maxTenants`` (a milli quantity --
    the virtual-capacity multiplier) and HBM is debited the tenant's
    budgeted share, so ``maxTenants`` slot allocations together consume
    at most the backing carve-out's budget and a whole-chip claim can
    never land on a chip with an active tenant."""
    per_core_hbm = host.hbm_bytes_per_chip // host.cores_per_chip
    core_value = "1"
    if dev.kind == DeviceKind.CHIP:
        idx = dev.chip.chip.index
        cores = [(idx, k) for k in range(host.cores_per_chip)]
    elif dev.kind == DeviceKind.PARTITION and dev.partition is not None:
        part = dev.partition
        cores = [
            (c // host.cores_per_chip, c % host.cores_per_chip)
            for c in part.spec.core_indices(host)
        ]
        if part.profile.max_tenants > 1:
            core_value = f"{part.tenant_core_milli}m"
        # Tenant HBM budget, spread over the carve-out's cores.
        per_core_hbm = part.tenant_hbm_bytes // max(len(cores), 1)
    elif dev.subslice is not None:
        cores = [
            (c // host.cores_per_chip, c % host.cores_per_chip)
            for c in dev.subslice.spec.core_indices(host)
        ]
    else:
        return []
    counters: dict[str, dict] = {}
    hbm_per_chip: dict[int, int] = {}
    for chip_idx, core_idx in cores:
        counters[f"core-{chip_idx}-{core_idx}"] = {"value": core_value}
        hbm_per_chip[chip_idx] = hbm_per_chip.get(chip_idx, 0) + per_core_hbm
    for chip_idx, hbm in hbm_per_chip.items():
        counters[f"hbm-{chip_idx}"] = {"value": str(hbm)}
    return [{"counterSet": COUNTER_SET, "counters": counters}]
