"""TPU-native Kubernetes DRA driver framework.

A brand-new framework with the capabilities of NVIDIA's k8s-dra-driver-gpu
(reference surveyed in /root/repo/SURVEY.md), designed TPU-first:

- ``tpulib``: C++/ctypes device layer enumerating TPU chips, ICI topology,
  and sub-slice partitions (replaces the reference's NVML cgo layer,
  reference cmd/gpu-kubelet-plugin/nvlib.go).
- ``api``: the ``resource.tpu.dra/v1beta1`` API group -- opaque device
  configs with Normalize/Validate and strict/non-strict decoders, plus the
  ComputeDomain / ComputeDomainClique CR types (reference
  api/nvidia.com/resource/v1beta1/).
- ``kubeletplugin``: the per-node ``tpu.dra.dev`` DRA driver -- chip
  enumeration -> ResourceSlice publication, two-phase checkpointed
  Prepare/Unprepare, CDI injection of /dev/accel* + libtpu + TPU_* env
  (reference cmd/gpu-kubelet-plugin/).
- ``computedomain``: controller + kubelet plugin + per-node daemon that
  gang-prepare multi-host ICI slices and bootstrap the JAX coordination
  service (reference cmd/compute-domain-{controller,kubelet-plugin,daemon}/).
- ``pkg``: shared infra -- feature gates, flock, workqueue, metrics,
  boot-id, minimal k8s REST client, DRA gRPC plumbing (reference pkg/).
- ``models`` / ``ops`` / ``parallel`` / ``train``: the TPU workload stack
  (JAX Llama-3, sharded training step, ring attention, collectives) that
  runs on slices prepared by this driver -- the reference exercises its
  fabric with external NCCL jobs; we ship the JAX analog in-tree.
"""

def _read_version() -> str:
    """Single source of truth: the repo-root VERSION file (reference:
    /root/reference/VERSION consumed by versions.mk). A distribution
    shipped without the file (the Dockerfile copies it) reports an
    explicitly-unknown version rather than a stale literal."""
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "VERSION")
    try:
        with open(path, encoding="utf-8") as f:
            return f.read().strip().lstrip("v")
    except OSError:
        return "0.0.0+unknown"


__version__ = _read_version()

DRIVER_NAME = "tpu.dra.dev"
COMPUTE_DOMAIN_DRIVER_NAME = "compute-domain.tpu.dra.dev"
API_GROUP = "resource.tpu.dra"
API_VERSION = "v1beta1"
