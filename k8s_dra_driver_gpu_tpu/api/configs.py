"""Opaque device-config types with Normalize/Validate.

Reference: api/nvidia.com/resource/v1beta1/{gpuconfig.go:29,
migconfig.go:28, vfiodeviceconfig.go:29, computedomainconfig.go:28-86,
sharing.go} -- every config implements Interface{Normalize,Validate}
(api.go:41-44).

TPU mapping: GpuConfig -> TpuConfig (whole-chip claims), MigDeviceConfig
-> SubSliceConfig (sub-slice carve-out claims), VfioDeviceConfig ->
PassthroughConfig, MPS -> MultiTenancy (co-tenant chip sharing with
per-client HBM limits).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from enum import Enum


class ValidationError(ValueError):
    pass


class TimeSlicingInterval(str, Enum):
    DEFAULT = "Default"
    SHORT = "Short"
    MEDIUM = "Medium"
    LONG = "Long"


class AllocationMode(str, Enum):
    SINGLE = "Single"
    ALL = "All"


_HBM_RE = re.compile(r"^(\d+)(Gi|Mi)?$")


def _parse_hbm(limit: str) -> int:
    """Parse an HBM limit like "8Gi"/"512Mi"/"1024" (bytes) to bytes."""
    m = _HBM_RE.match(limit)
    if not m:
        raise ValidationError(f"invalid HBM limit {limit!r}")
    n = int(m.group(1))
    unit = m.group(2)
    if unit == "Gi":
        return n << 30
    if unit == "Mi":
        return n << 20
    return n


@dataclass
class TimeSlicingConfig:
    """Temporal sharing: chip time-slice interval.

    Reference: sharing.go:33-39 (TimeSlicingSettings Default/Short/
    Medium/Long).
    """

    interval: str = TimeSlicingInterval.DEFAULT.value

    def normalize(self) -> None:
        if not self.interval:
            self.interval = TimeSlicingInterval.DEFAULT.value

    def validate(self) -> None:
        values = [i.value for i in TimeSlicingInterval]
        if self.interval not in values:
            raise ValidationError(
                f"unknown time-slicing interval {self.interval!r}; "
                f"must be one of {values}"
            )


@dataclass
class MultiTenancyConfig:
    """Spatial co-tenancy on one chip (MPS analog): bounded client count
    with per-client HBM limits, normalized per device.

    Reference: sharing.go:190-220 (MPS activeThreadPercentage + pinned
    device-memory limits with per-device override normalization).
    """

    max_clients: int | None = None
    # Default HBM limit applied to every client; per-device overrides win.
    hbm_limit: str | None = None
    per_device_hbm_limits: dict[str, str] = field(default_factory=dict)

    def normalize(self) -> None:
        # Fold the default limit into an explicit per-device map entry
        # ("*" wildcard), mirroring the reference's normalization of the
        # default memory limit into per-device entries.
        if self.hbm_limit and "*" not in self.per_device_hbm_limits:
            self.per_device_hbm_limits["*"] = self.hbm_limit

    def validate(self) -> None:
        if self.max_clients is not None and self.max_clients < 1:
            raise ValidationError("maxClients must be >= 1")
        for dev, lim in self.per_device_hbm_limits.items():
            _parse_hbm(lim)  # raises on malformed
            if dev != "*" and not dev:
                raise ValidationError("empty device key in hbm limits")

    def hbm_limit_bytes_for(self, device: str) -> int | None:
        lim = self.per_device_hbm_limits.get(
            device, self.per_device_hbm_limits.get("*")
        )
        return _parse_hbm(lim) if lim else None


@dataclass
class Sharing:
    """Sharing strategy union (exactly one member set after validate).

    Reference: sharing.go Sharing{strategy, timeSlicingConfig, mpsConfig}.
    """

    strategy: str = "TimeSlicing"  # TimeSlicing | MultiTenancy
    time_slicing: TimeSlicingConfig | None = None
    multi_tenancy: MultiTenancyConfig | None = None

    def normalize(self) -> None:
        if self.strategy == "TimeSlicing" and self.time_slicing is None:
            self.time_slicing = TimeSlicingConfig()
        if self.time_slicing:
            self.time_slicing.normalize()
        if self.multi_tenancy:
            self.multi_tenancy.normalize()

    def validate(self) -> None:
        if self.strategy == "TimeSlicing":
            if self.multi_tenancy is not None:
                raise ValidationError(
                    "multiTenancy config set with TimeSlicing strategy"
                )
            if self.time_slicing:
                self.time_slicing.validate()
        elif self.strategy == "MultiTenancy":
            if self.time_slicing is not None:
                raise ValidationError(
                    "timeSlicing config set with MultiTenancy strategy"
                )
            if self.multi_tenancy is None:
                raise ValidationError("multiTenancy config missing")
            self.multi_tenancy.validate()
        else:
            raise ValidationError(f"unknown sharing strategy {self.strategy!r}")

    @property
    def is_time_slicing(self) -> bool:
        return self.strategy == "TimeSlicing"

    @property
    def is_multi_tenancy(self) -> bool:
        return self.strategy == "MultiTenancy"


@dataclass
class TpuConfig:
    """Config for whole-chip claims (GpuConfig analog, gpuconfig.go:29)."""

    KIND = "TpuConfig"

    sharing: Sharing | None = None

    def normalize(self) -> None:
        if self.sharing is None:
            self.sharing = Sharing()
        self.sharing.normalize()

    def validate(self) -> None:
        if self.sharing:
            self.sharing.validate()


@dataclass
class SubSliceConfig:
    """Config for sub-slice carve-out claims (MigDeviceConfig analog,
    migconfig.go:28). Also the config kind for partition devices
    (pkg/partition): a tenant claim targeting an OVERSUBSCRIBED
    partition (one whose device advertises ``oversubscribeSlots`` > 1)
    must set ``oversubscribe: true`` -- the explicit opt-in to sharing
    a carve-out cooperatively with up to N-1 other tenants."""

    KIND = "SubSliceConfig"

    sharing: Sharing | None = None
    # Opt-in to time-slice oversubscription on a shared partition
    # device. Preparing an oversubscribed partition WITHOUT this flag
    # fails: a workload must never be co-scheduled onto shared cores it
    # did not agree to share.
    oversubscribe: bool = False

    def normalize(self) -> None:
        if self.sharing is None:
            self.sharing = Sharing()
        self.sharing.normalize()

    def validate(self) -> None:
        if self.sharing:
            self.sharing.validate()
        if self.oversubscribe and self.sharing and \
                self.sharing.is_multi_tenancy:
            raise ValidationError(
                "oversubscribe provisions its own per-tenant tenancy "
                "enforcement; a MultiTenancy sharing config cannot be "
                "combined with it"
            )


@dataclass
class PassthroughConfig:
    """Config for vfio passthrough claims (VfioDeviceConfig analog,
    vfiodeviceconfig.go:29)."""

    KIND = "PassthroughConfig"

    # "legacy" (/dev/vfio/<group>) or "iommufd" (/dev/vfio/devices/*).
    iommu_mode: str = "legacy"

    def normalize(self) -> None:
        if not self.iommu_mode:
            self.iommu_mode = "legacy"

    def validate(self) -> None:
        if self.iommu_mode not in ("legacy", "iommufd"):
            raise ValidationError(
                f"unknown iommu mode {self.iommu_mode!r}"
            )


@dataclass
class ComputeDomainChannelConfig:
    """Workload-side ComputeDomain claim config
    (computedomainconfig.go:28-56)."""

    KIND = "ComputeDomainChannelConfig"

    domain_id: str = ""
    allocation_mode: str = AllocationMode.SINGLE.value

    def normalize(self) -> None:
        if not self.allocation_mode:
            self.allocation_mode = AllocationMode.SINGLE.value

    def validate(self) -> None:
        if not self.domain_id:
            raise ValidationError("domainID must be set")
        modes = [m.value for m in AllocationMode]
        if self.allocation_mode not in modes:
            raise ValidationError(
                f"unknown allocationMode {self.allocation_mode!r}"
            )


@dataclass
class ComputeDomainDaemonConfig:
    """Daemon-side ComputeDomain claim config
    (computedomainconfig.go:58-86)."""

    KIND = "ComputeDomainDaemonConfig"

    domain_id: str = ""

    def normalize(self) -> None:
        pass

    def validate(self) -> None:
        if not self.domain_id:
            raise ValidationError("domainID must be set")
