"""The ``resource.tpu.dra/v1beta1`` API group.

Reference: api/nvidia.com/resource/v1beta1/ (opaque-config types with
Normalize()/Validate(), strict + non-strict decoders at api.go:41-98, and
the ComputeDomain/ComputeDomainClique CRDs).
"""

from .configs import (
    AllocationMode,
    ComputeDomainChannelConfig,
    ComputeDomainDaemonConfig,
    MultiTenancyConfig,
    PassthroughConfig,
    Sharing,
    SubSliceConfig,
    TimeSlicingConfig,
    TimeSlicingInterval,
    TpuConfig,
    ValidationError,
)
from .computedomain import (
    ComputeDomain,
    ComputeDomainClique,
    ComputeDomainNode,
    ComputeDomainStatusValue,
)
from .decode import DecodeError, decode_config, nonstrict_decode, strict_decode

API_VERSION = "resource.tpu.dra/v1beta1"

__all__ = [
    "API_VERSION",
    "AllocationMode",
    "ComputeDomain",
    "ComputeDomainChannelConfig",
    "ComputeDomainClique",
    "ComputeDomainDaemonConfig",
    "ComputeDomainNode",
    "ComputeDomainStatusValue",
    "DecodeError",
    "MultiTenancyConfig",
    "PassthroughConfig",
    "Sharing",
    "SubSliceConfig",
    "TimeSlicingConfig",
    "TimeSlicingInterval",
    "TpuConfig",
    "ValidationError",
    "decode_config",
    "nonstrict_decode",
    "strict_decode",
]
