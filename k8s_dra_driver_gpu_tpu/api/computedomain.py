"""ComputeDomain / ComputeDomainClique CR types.

Reference: api/nvidia.com/resource/v1beta1/computedomain.go:38-143 and
computedomainclique.go:29-71. A ComputeDomain gang-prepares a contiguous
multi-host ICI slice; its status aggregates per-node daemon readiness. A
ComputeDomainClique carries per-ICI-domain daemon membership (one clique
per tightly-coupled slice; cross-clique traffic rides DCN).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..computedomain import expected_slices as _expected_slices


class ComputeDomainStatusValue:
    READY = "Ready"
    NOT_READY = "NotReady"


@dataclass
class ComputeDomainChannel:
    resource_claim_template_name: str = ""
    allocation_mode: str = "Single"


@dataclass
class ComputeDomainNode:
    """Per-node rendezvous record (computedomain.go status.nodes)."""

    name: str = ""
    ip_address: str = ""
    clique_id: str = ""
    index: int = -1  # stable worker index within the clique
    status: str = ComputeDomainStatusValue.NOT_READY

    @classmethod
    def from_dict(cls, d: dict) -> "ComputeDomainNode":
        return cls(
            name=d.get("name", ""),
            ip_address=d.get("ipAddress", ""),
            clique_id=d.get("cliqueID", ""),
            index=d.get("index", -1),
            status=d.get("status", ComputeDomainStatusValue.NOT_READY),
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "ipAddress": self.ip_address,
            "cliqueID": self.clique_id,
            "index": self.index,
            "status": self.status,
        }


@dataclass
class ComputeDomain:
    """The ComputeDomain CR (namespaced)."""

    name: str = ""
    namespace: str = "default"
    uid: str = ""
    # Spec.
    num_nodes: int = 0
    channel_resource_claim_template: str = ""
    channel_allocation_mode: str = "Single"
    # Desired ICI slice topology, e.g. "2x2x4" (TPU-native addition: the
    # reference sizes domains by numNodes only; on TPU the slice shape is
    # the unit of gang scheduling).
    topology: str = ""
    # Cross-slice: numNodes hosts split evenly over this many ICI
    # slices (one clique per slice); >1 adds the MEGASCALE-style DCN
    # env to the channel contract (TPU-native addition: the reference's
    # IMEX domains cannot span NVLink partitions).
    num_slices: int = 1
    # Status.
    status: str = ComputeDomainStatusValue.NOT_READY
    nodes: list[ComputeDomainNode] = field(default_factory=list)
    # Metadata bookkeeping.
    finalizers: list[str] = field(default_factory=list)
    generation: int = 0

    @classmethod
    def from_dict(cls, d: dict) -> "ComputeDomain":
        meta = d.get("metadata", {})
        spec = d.get("spec", {})
        status = d.get("status", {})
        channel = spec.get("channel") or {}
        rct = channel.get("resourceClaimTemplate") or {}
        return cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            uid=meta.get("uid", ""),
            num_nodes=spec.get("numNodes", 0),
            num_slices=_expected_slices(spec),
            channel_resource_claim_template=rct.get("name", ""),
            channel_allocation_mode=channel.get("allocationMode", "Single"),
            topology=spec.get("topology", ""),
            status=status.get("status", ComputeDomainStatusValue.NOT_READY),
            nodes=[
                ComputeDomainNode.from_dict(n) for n in status.get("nodes", [])
            ],
            finalizers=list(meta.get("finalizers", [])),
            generation=meta.get("generation", 0),
        )

    def to_dict(self) -> dict:
        return {
            "apiVersion": "resource.tpu.dra/v1beta1",
            "kind": "ComputeDomain",
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                "uid": self.uid,
                "finalizers": self.finalizers,
                "generation": self.generation,
            },
            "spec": {
                "numNodes": self.num_nodes,
                "numSlices": self.num_slices,
                "topology": self.topology,
                "channel": {
                    "resourceClaimTemplate": {
                        "name": self.channel_resource_claim_template
                    },
                    "allocationMode": self.channel_allocation_mode,
                },
            },
            "status": {
                "status": self.status,
                "nodes": [n.to_dict() for n in self.nodes],
            },
        }


@dataclass
class ComputeDomainClique:
    """Per-ICI-clique daemon membership CR, named "<cdUID>.<cliqueID>"
    (computedomainclique.go:29-71; written by daemons, read by the
    controller and by workload bootstrap)."""

    name: str = ""
    namespace: str = "default"
    uid: str = ""
    compute_domain_uid: str = ""
    clique_id: str = ""
    daemons: list[ComputeDomainNode] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "ComputeDomainClique":
        meta = d.get("metadata", {})
        spec = d.get("spec", {})
        return cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            uid=meta.get("uid", ""),
            compute_domain_uid=spec.get("computeDomainUID", ""),
            clique_id=spec.get("cliqueID", ""),
            daemons=[
                ComputeDomainNode.from_dict(n)
                for n in d.get("status", {}).get("daemons", [])
            ],
        )

    def to_dict(self) -> dict:
        return {
            "apiVersion": "resource.tpu.dra/v1beta1",
            "kind": "ComputeDomainClique",
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                "uid": self.uid,
            },
            "spec": {
                "computeDomainUID": self.compute_domain_uid,
                "cliqueID": self.clique_id,
            },
            "status": {"daemons": [n.to_dict() for n in self.daemons]},
        }
