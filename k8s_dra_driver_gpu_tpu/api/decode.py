"""Strict and non-strict decoders for opaque device configs.

Reference: api.go:46-57 -- the StrictDecoder rejects unknown fields (used
on *user input*: claim parameters, webhook admission), the
NonstrictDecoder tolerates them (used on *checkpoint data*, where a newer
schema may have written fields an older binary doesn't know).
"""

from __future__ import annotations

from dataclasses import fields as dc_fields
from typing import Any, Type

from . import configs
from .configs import (
    ComputeDomainChannelConfig,
    ComputeDomainDaemonConfig,
    MultiTenancyConfig,
    PassthroughConfig,
    Sharing,
    SubSliceConfig,
    TimeSlicingConfig,
    TpuConfig,
)

API_VERSION = "resource.tpu.dra/v1beta1"


class DecodeError(ValueError):
    pass


_KINDS: dict[str, Type] = {
    c.KIND: c
    for c in (
        TpuConfig,
        SubSliceConfig,
        PassthroughConfig,
        ComputeDomainChannelConfig,
        ComputeDomainDaemonConfig,
    )
}

# JSON field name -> dataclass attribute per type.
_FIELD_MAPS: dict[Type, dict[str, str]] = {
    TpuConfig: {"sharing": "sharing"},
    SubSliceConfig: {"sharing": "sharing",
                     "oversubscribe": "oversubscribe"},
    PassthroughConfig: {"iommuMode": "iommu_mode"},
    ComputeDomainChannelConfig: {
        "domainID": "domain_id",
        "allocationMode": "allocation_mode",
    },
    ComputeDomainDaemonConfig: {"domainID": "domain_id"},
    Sharing: {
        "strategy": "strategy",
        "timeSlicing": "time_slicing",
        "multiTenancy": "multi_tenancy",
    },
    TimeSlicingConfig: {"interval": "interval"},
    MultiTenancyConfig: {
        "maxClients": "max_clients",
        "hbmLimit": "hbm_limit",
        "perDeviceHbmLimits": "per_device_hbm_limits",
    },
}

_NESTED: dict[tuple[Type, str], Type] = {
    (TpuConfig, "sharing"): Sharing,
    (SubSliceConfig, "sharing"): Sharing,
    (Sharing, "time_slicing"): TimeSlicingConfig,
    (Sharing, "multi_tenancy"): MultiTenancyConfig,
}


def _decode_into(cls: Type, data: dict, strict: bool, path: str) -> Any:
    if not isinstance(data, dict):
        raise DecodeError(f"{path}: expected object, got {type(data).__name__}")
    fmap = _FIELD_MAPS[cls]
    kwargs: dict[str, Any] = {}
    for json_key, value in data.items():
        if json_key not in fmap:
            if strict:
                raise DecodeError(f"{path}: unknown field {json_key!r}")
            continue
        attr = fmap[json_key]
        nested = _NESTED.get((cls, attr))
        if nested is not None and value is not None:
            value = _decode_into(nested, value, strict, f"{path}.{json_key}")
        kwargs[attr] = value
    try:
        return cls(**kwargs)
    except TypeError as e:
        raise DecodeError(f"{path}: {e}") from e


def decode_config(parameters: dict, strict: bool = True) -> Any:
    """Decode an opaque-config ``parameters`` object (with apiVersion and
    kind) into its typed config. Does NOT normalize/validate -- callers
    run that explicitly (reference runs Normalize+Validate at both
    admission and prepare time)."""
    if not isinstance(parameters, dict):
        raise DecodeError("opaque parameters must be an object")
    api_version = parameters.get("apiVersion", "")
    if api_version != API_VERSION:
        raise DecodeError(
            f"unsupported apiVersion {api_version!r} (want {API_VERSION})"
        )
    kind = parameters.get("kind", "")
    cls = _KINDS.get(kind)
    if cls is None:
        raise DecodeError(f"unknown config kind {kind!r}")
    body = {
        k: v for k, v in parameters.items() if k not in ("apiVersion", "kind")
    }
    return _decode_into(cls, body, strict, kind)


def strict_decode(parameters: dict) -> Any:
    """User-input decoder: unknown fields are errors (api.go:46-50)."""
    return decode_config(parameters, strict=True)


def nonstrict_decode(parameters: dict) -> Any:
    """Checkpoint-data decoder: unknown fields ignored (api.go:52-57)."""
    return decode_config(parameters, strict=False)


def encode_config(cfg: Any) -> dict:
    """Typed config -> opaque parameters dict (inverse of decode)."""
    cls = type(cfg)
    fmap = _FIELD_MAPS[cls]
    out: dict[str, Any] = {"apiVersion": API_VERSION}
    if hasattr(cls, "KIND"):
        out["kind"] = cls.KIND
    rev = {attr: json_key for json_key, attr in fmap.items()}
    for f in dc_fields(cfg):
        value = getattr(cfg, f.name)
        if value is None:
            continue
        if (cls, f.name) in _NESTED:
            inner = encode_config(value)
            inner.pop("apiVersion", None)
            value = inner
        out[rev[f.name]] = value
    return out
